"""Fleet-wide training observability (r13).

The training-side analog of ``serving``'s tracing + metrics stack, in
four connected pieces:

* **rank-aware telemetry** — ``on_step_record`` (called from
  ``telemetry.step_end``) stamps every JSONL step record with
  ``rank``/``world_size``, and at a configurable stride (default every
  16 steps — never a new per-step sync) piggybacks an allgather of a
  tiny packed step-stats vector so every rank sees per-rank ``step_ms``,
  allreduce-wait, ``compute_ms``, ``peak_live_bytes`` and examples/sec
  as a ``{"record": "fleet"}`` JSONL event;
* **straggler + anomaly watchdog** — rolling per-rank baselines over
  the fleet view flag ranks whose compute or allreduce-wait skew
  exceeds a threshold for K consecutive windows, plus local detectors
  for NaN/Inf loss, gradient-norm spikes and step-time regressions,
  emitted as ``{"record": "anomaly"}`` events, counted in telemetry
  (``fleet.anomaly.*``) and surfaced on an optional callback (warn by
  default, halt opt-in via :class:`WatchdogHalt`);
* **training flight recorder** — a bounded ring of the last N step
  records + fleet views + anomalies, dumped (rate-limited, atomic,
  never raises) on SIGTERM drain, watchdog halt and restart, and
  embedded into memwatch OOM post-mortems as ``recent_steps``;
* **live scrape** — :class:`MetricsEndpoint` exposes the same
  ``/metrics`` + ``/healthz`` surface the serving stack has, rendered
  by the shared ``telemetry.promtext`` module.

Disabled cost is a single module-global boolean check per step record
(the PR 2/12 pattern); nothing here ever raises into training except
the opt-in :class:`WatchdogHalt`.

Environment knobs: ``MXNET_FLEET=1`` autostarts at import;
``MXNET_FLEET_STRIDE`` (16), ``MXNET_FLEET_RING`` (256),
``MXNET_FLEET_SKEW`` (1.5), ``MXNET_FLEET_WINDOWS`` (3),
``MXNET_FLEET_HALT`` (0) tune the watchdog; ``MXNET_FLEET_DUMP`` names
the flight-dump path (a ``{rank}`` placeholder expands per rank) and
additionally enables periodic dumps at each exchange stride plus an
atexit dump, so even a SIGKILL'd rank leaves a readable dump behind.

Schema details in docs/observability.md.
"""
from __future__ import annotations

import atexit
import collections
import json
import math
import os
import statistics
import sys
import threading
import time

from .. import sanitizer as _sanitizer

from . import promtext
from .sinks import _json_default

__all__ = [
    "enable", "disable", "is_enabled", "world", "rank",
    "on_step_record", "detect_skew", "detect_nan", "detect_spike",
    "growth_streak",
    "Watchdog", "WatchdogHalt", "recent", "clear", "dump", "incident",
    "last_view", "halt_requested", "MetricsEndpoint", "metrics_url",
]

# -- defaults (env-overridable at enable() time) ------------------------

#: exchange the packed step-stats vector every N steps
DEFAULT_STRIDE = 16
#: flight-recorder depth (step records + fleet views + anomalies)
RING_CAPACITY = 256
#: a rank is skewed when value / fleet-median exceeds this
SKEW_THRESHOLD = 1.5
#: consecutive skewed exchange windows before the watchdog fires
CONSECUTIVE = 3
#: grad-norm spike = value / rolling-median above this
SPIKE_FACTOR = 10.0
#: step-time regression = value / rolling-median above this
REGRESSION_FACTOR = 2.0
#: local spike/regression detectors stay quiet until this much history
MIN_HISTORY = 8
#: grad-norm explosion = this much growth per observed window, sustained
#: for ``consecutive`` windows (same streak machinery as stragglers)
GROWTH_FACTOR = 2.0
#: per-reason minimum spacing between incident dumps
DUMP_INTERVAL_S = 5.0


class WatchdogHalt(RuntimeError):
    """Raised out of ``on_step_record`` (and therefore out of
    ``telemetry.step_end``, at a step boundary) when the watchdog sees
    an anomaly and halt was opted into."""


_enabled = False
_lock = _sanitizer.wrap_lock(threading.Lock(), "fleet._lock")
_ring = collections.deque(maxlen=RING_CAPACITY)
_ring_lock = _sanitizer.wrap_lock(threading.Lock(), "fleet._ring_lock")
_last_dump = {}      # reason -> monotonic time of last incident dump
_watchdog = None
_last_view = None    # most recent fleet-view record
_halted = False
_stride = DEFAULT_STRIDE
_on_anomaly = None
_halt = False
_endpoint = None
_world_cache = None
_atexit_installed = False


def _telemetry():
    # resolved lazily; the parent package imports this module
    return sys.modules.get("mxnet_tpu.telemetry")


def _parallel():
    # never trigger the parallel (and therefore jax) import from here
    return sys.modules.get("mxnet_tpu.parallel")


def world():
    """``(rank, world_size)`` via ``elastic.world_info()``, cached once
    the answer is authoritative (live process group, launcher env, or
    the parallel module already imported)."""
    global _world_cache
    cached = _world_cache
    if cached is not None:
        return cached
    from .. import elastic
    r, n = elastic.world_info()
    if n > 1 or os.environ.get("MXT_NUM_PROCESSES") or _parallel() is not None:
        with _lock:
            _world_cache = (r, n)
    return r, n


def rank():
    return world()[0]


# -- pure detector functions (unit-tested directly) ---------------------

def detect_skew(values, threshold=SKEW_THRESHOLD):
    """Indices whose value exceeds ``threshold`` x the median of
    ``values``. Pure; returns ``[]`` for degenerate input. ``None``
    entries (gaps in strided records) are skipped, never flagged."""
    pairs = [(i, float(v)) for i, v in enumerate(values) if v is not None]
    if len(pairs) < 2:
        return []
    med = statistics.median(v for _, v in pairs)
    if med <= 0.0:
        return []
    return [i for i, v in pairs if v / med > threshold]


def detect_nan(value):
    """True when ``value`` is NaN or +/-Inf (or not a number at all)."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return True
    return math.isnan(f) or math.isinf(f)


def detect_spike(value, history, factor=SPIKE_FACTOR,
                 min_history=MIN_HISTORY):
    """True when ``value`` exceeds ``factor`` x the median of
    ``history``; quiet until ``min_history`` samples exist. ``None``
    gaps (strided records miss metrics off-stride) are tolerated in
    both the history and the value."""
    if value is None:
        return False
    hist = [float(v) for v in history if v is not None]
    if len(hist) < min_history:
        return False
    med = statistics.median(hist)
    if med <= 0.0:
        return False
    return float(value) / med > factor


def growth_streak(history, factor=GROWTH_FACTOR):
    """Length of the trailing run of consecutive windows in ``history``
    where each value grew by more than ``factor`` x over its
    predecessor. Pure; ``None`` gaps break the streak; non-positive
    predecessors never count as growth."""
    vals = list(history)
    streak = 0
    for prev, cur in zip(reversed(vals[:-1]), reversed(vals[1:])):
        if prev is None or cur is None:
            break
        prev, cur = float(prev), float(cur)
        if prev <= 0.0 or cur <= factor * prev:
            break
        streak += 1
    return streak


class Watchdog:
    """Rolling-baseline anomaly detection.

    ``observe_step`` runs the local detectors over one step record;
    ``observe_fleet`` runs the cross-rank skew detectors over one fleet
    view, tracking per-``(metric, rank)`` consecutive-window streaks.
    Both return lists of anomaly dicts (``kind`` + detail fields); the
    caller stamps rank/step/wall-time and emits.
    """

    #: (fleet-view column, anomaly kind) pairs the streak tracker watches
    FLEET_METRICS = (("compute_ms", "straggler"),
                     ("allreduce_wait_ms", "allreduce_wait_skew"))

    def __init__(self, skew_threshold=SKEW_THRESHOLD, consecutive=CONSECUTIVE,
                 spike_factor=SPIKE_FACTOR, regression_factor=REGRESSION_FACTOR,
                 min_history=MIN_HISTORY, growth_factor=GROWTH_FACTOR):
        self.skew_threshold = float(skew_threshold)
        self.consecutive = int(consecutive)
        self.spike_factor = float(spike_factor)
        self.regression_factor = float(regression_factor)
        self.min_history = int(min_history)
        self.growth_factor = float(growth_factor)
        self._grad_hist = collections.deque(maxlen=64)
        self._step_hist = collections.deque(maxlen=64)
        self._streaks = {}   # (metric, rank) -> consecutive skewed windows

    def observe_step(self, record):
        out = []
        loss = record.get("loss")
        if loss is not None and detect_nan(loss):
            out.append({"kind": "nan_loss", "value": repr(loss)})
        num = record.get("numerics") or {}
        first_nan = num.get("first_nan")
        if first_nan:
            # layer-resolved provenance from the in-compile stats tier:
            # the anomaly names (layer, param path); _emit_anomaly
            # stamps the rank, completing "rank R, path, step S"
            out.append({"kind": "nan_tensor",
                        "path": first_nan.get("path"),
                        "layer": first_nan.get("layer"),
                        "nan": first_nan.get("nan"),
                        "inf": first_nan.get("inf")})
        gn = record.get("grad_norm")
        if gn is None:
            # the numerics tier aggregates grad.* l2 at its stride —
            # feeds the spike/explosion detectors with no extra wiring
            gn = num.get("grad_norm")
        if gn is not None:
            if detect_nan(gn):
                out.append({"kind": "nan_grad", "value": repr(gn)})
            else:
                gn = float(gn)
                if detect_spike(gn, self._grad_hist, self.spike_factor,
                                self.min_history):
                    out.append({"kind": "grad_spike", "value": gn,
                                "median": statistics.median(self._grad_hist),
                                "factor": self.spike_factor})
                self._grad_hist.append(gn)
                streak = growth_streak(self._grad_hist,
                                       self.growth_factor)
                if streak >= self.consecutive:
                    out.append({"kind": "grad_norm_explosion",
                                "value": gn, "windows": streak,
                                "factor": self.growth_factor})
        sm = record.get("step_ms")
        if sm is not None and not detect_nan(sm):
            sm = float(sm)
            if detect_spike(sm, self._step_hist, self.regression_factor,
                            self.min_history):
                out.append({"kind": "step_regression", "value": sm,
                            "median": statistics.median(self._step_hist),
                            "factor": self.regression_factor})
            self._step_hist.append(sm)
        return out

    def observe_fleet(self, step, view):
        out = []
        for metric, kind in self.FLEET_METRICS:
            values = view.get(metric)
            if not values or len(values) < 2:
                continue
            flagged = set(detect_skew(values, self.skew_threshold))
            med = statistics.median(float(v) for v in values)
            for r in range(len(values)):
                key = (metric, r)
                if r in flagged:
                    streak = self._streaks.get(key, 0) + 1
                    self._streaks[key] = streak
                    if streak >= self.consecutive:
                        out.append({
                            "kind": kind, "culprit": r, "metric": metric,
                            "value": float(values[r]),
                            "ratio": float(values[r]) / med if med else 0.0,
                            "windows": streak,
                        })
                else:
                    self._streaks.pop(key, None)
        return out


# -- flight recorder ----------------------------------------------------

def recent(n=None):
    """The last ``n`` (default: all) ring entries, oldest first."""
    with _ring_lock:
        items = list(_ring)
    if n is not None:
        items = items[-int(n):]
    return items


def clear():
    """Drop ring contents and per-run detector/dump state."""
    global _last_view, _halted, _world_cache
    with _ring_lock:
        _ring.clear()
    with _lock:
        _last_dump.clear()
        _last_view = None
        _halted = False
        _world_cache = None


def _dump_path():
    tmpl = os.environ.get("MXNET_FLEET_DUMP")
    if tmpl:
        return tmpl.replace("{rank}", str(world()[0]))
    return "fleet_record_%d.json" % os.getpid()


def dump(path=None, reason="manual", context=None):
    """Write the flight-recorder ring as a single JSON document.

    Atomic (tmp + rename) so a kill mid-write never clobbers the
    previous good dump. Returns the path written."""
    r, n = world()
    if path is None:
        path = _dump_path()
    doc = {
        "record": "flight_recorder",
        "kind": "fleet",
        "reason": reason,
        "wall_time": time.time(),
        "rank": r,
        "world_size": n,
        "context": context or {},
        "records": recent(),
    }
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f, default=_json_default)
        f.write("\n")
    os.replace(tmp, path)
    return path


def incident(reason, context=None, path=None):
    """Rate-limited :func:`dump`; never raises. Returns the path
    written, or ``None`` when disabled, throttled, or failed."""
    if not _enabled:
        return None
    try:
        now = time.monotonic()
        with _lock:
            last = _last_dump.get(reason)
            if last is not None and now - last < DUMP_INTERVAL_S:
                return None
            _last_dump[reason] = now
        return dump(path, reason, context)
    except Exception:
        return None   # the flight recorder never raises into training


def _atexit_dump():
    # SIGTERM-drain / normal-exit dump; SIGKILL relies on the periodic
    # stride dumps instead. Gated on the env var so plain local runs
    # never litter the cwd.
    if _enabled and os.environ.get("MXNET_FLEET_DUMP"):
        try:
            dump(reason="exit")
        except Exception:
            pass


# -- the step hook ------------------------------------------------------

def on_step_record(record):
    """Called from ``telemetry.step_end`` for every step record.

    Disabled cost is this one boolean check. Mutates ``record`` in
    place (adds ``rank``/``world_size``) before the sinks see it."""
    if not _enabled:
        return
    try:
        _observe(record)
    except WatchdogHalt:
        raise
    except Exception:
        pass   # fleet telemetry never raises into training


def _observe(record):
    global _last_view
    tel = _telemetry()
    r, n = world()
    record["rank"] = r
    record["world_size"] = n
    with _ring_lock:
        _ring.append(dict(record))
    wd = _watchdog
    anomalies = list(wd.observe_step(record)) if wd is not None else []
    step = record.get("step")
    if step is not None and _stride > 0 and step % _stride == 0:
        view = _fleet_exchange(record)
        with _lock:
            _last_view = view
        with _ring_lock:
            _ring.append(view)
        if tel is not None:
            tel.count("fleet.exchange")
            tel.gauge("fleet.exchange_ms", view["exchange_ms"])
            tel.emit(view)
        if wd is not None:
            anomalies.extend(wd.observe_fleet(step, view))
        if os.environ.get("MXNET_FLEET_DUMP"):
            incident("stride", context={"step": step})
    for a in anomalies:
        _emit_anomaly(a, record)


def _fleet_exchange(record):
    """Allgather the packed per-rank stats vector and build the
    ``{"record": "fleet"}`` view. Stride-gated from ``_observe`` —
    never a per-step sync; single-process runs build a one-row view
    with no collective at all."""
    r, n = world()
    counters = record.get("counters") or {}
    phases = record.get("phases_ms") or {}
    step_ms = float(record.get("step_ms") or 0.0)
    wait_ms = float(counters.get("trainer.allreduce_wait_ms")
                    or phases.get("trainer.allreduce") or 0.0)
    # with a per-step allreduce barrier every rank's step_ms equalizes;
    # the straggler is the rank with high COMPUTE and low wait, so the
    # exchange carries compute_ms explicitly
    compute_ms = max(step_ms - wait_ms, 0.0)
    # nan provenance rides the exchange as a layer index (-1 = clean):
    # every rank learns WHO diverged and WHERE from one allgather
    first_nan = (record.get("numerics") or {}).get("first_nan") or {}
    nan_layer = float(first_nan.get("layer", -1) if first_nan else -1)
    # duty cycle (compute_ms / step_ms) rides as a 7th float: the
    # fleet's MFU proxy, so one allgather also answers "which rank is
    # spending its step on something other than compute"
    from . import capacity as _cap
    duty = _cap.duty_cycle(compute_ms, step_ms)
    vec = [step_ms, wait_ms, compute_ms,
           float(record.get("peak_live_bytes") or 0.0),
           float(record.get("examples_per_sec") or 0.0),
           nan_layer, duty]
    t0 = time.perf_counter()
    rows = None
    pl = _parallel()
    if pl is not None and n > 1:
        rows = [[float(x) for x in row]
                for row in pl.process_gather_hostvec(vec)]
    if rows is None:
        rows = [vec]
    exchange_ms = (time.perf_counter() - t0) * 1e3
    cols = list(zip(*rows))
    with _lock:
        # already paying an allgather here; snapshot config consistently
        wd = _watchdog
        stride = _stride
    thresh = wd.skew_threshold if wd is not None else SKEW_THRESHOLD
    view = {
        "record": "fleet",
        "step": record.get("step"),
        "stride": stride,
        "rank": r,
        "world_size": len(rows),
        "wall_time": time.time(),
        "step_ms": list(cols[0]),
        "allreduce_wait_ms": list(cols[1]),
        "compute_ms": list(cols[2]),
        "peak_live_bytes": list(cols[3]),
        "examples_per_sec": list(cols[4]),
        # per-rank first-NaN layer indices (-1 = clean); older peers'
        # 5-column vectors simply omit the column
        "first_nan_layer": ([int(v) for v in cols[5]]
                            if len(cols) > 5 else [-1] * len(rows)),
        # per-rank duty cycle (compute_ms / step_ms in [0, 1]); rows
        # gathered from older 6-column peers render as 0.0 (unknown)
        "duty_cycle": ([round(float(v), 4) for v in cols[6]]
                       if len(cols) > 6 else [0.0] * len(rows)),
        "exchange_ms": exchange_ms,
    }
    view["stragglers"] = detect_skew(view["compute_ms"], thresh)
    return view


def _emit_anomaly(anomaly, record):
    global _halted
    tel = _telemetry()
    r, n = world()
    evt = {"record": "anomaly", "step": record.get("step"),
           "rank": r, "world_size": n, "wall_time": time.time()}
    evt.update(anomaly)
    with _ring_lock:
        _ring.append(evt)
    if tel is not None:
        tel.count("fleet.anomaly")
        tel.count("fleet.anomaly." + evt["kind"])
        tel.emit(evt)
    with _lock:
        # anomalies are rare; snapshot the callback + halt opt-in
        # consistently against a concurrent configure()
        cb = _on_anomaly
        halt = _halt
    if cb is not None:
        try:
            cb(evt)
        except Exception:
            pass
    else:
        print("[mxnet_tpu.fleet] anomaly %s at step %s (rank %d/%d): %s"
              % (evt["kind"], evt.get("step"), r, n,
                 {k: v for k, v in anomaly.items() if k != "kind"}),
              file=sys.stderr)
    if halt:
        with _lock:
            _halted = True
        incident("watchdog_halt", context={"anomaly": evt})
        raise WatchdogHalt("watchdog halt: %s at step %s"
                           % (evt["kind"], evt.get("step")))


def halt_requested():
    """True once the watchdog has halted this process (surfaced as 503
    on ``/healthz``)."""
    with _lock:
        return _halted


def last_view():
    """The most recent fleet-view record, or ``None``."""
    with _lock:
        return _last_view


# -- live /metrics + /healthz for a training rank -----------------------

class MetricsEndpoint:
    """Tiny stdlib HTTP endpoint for a TRAINING rank: ``/metrics``
    renders the process's telemetry snapshot via the shared
    ``promtext`` renderer (the serving stack's exact conventions) plus
    fleet gauges; ``/healthz`` returns 200, or 503 once the watchdog
    has halted. ``port=0`` picks a free port (see :attr:`url`)."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._host = host
        self._port = int(port)
        self._server = None
        self._thread = None

    def start(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):   # noqa: N802 - stdlib API
                try:
                    if self.path.startswith("/metrics"):
                        body = prometheus_text().encode()
                        ctype = "text/plain; version=0.0.4"
                        code = 200
                    elif self.path.startswith("/healthz"):
                        r, n = world()
                        view = last_view()
                        halted = halt_requested()
                        payload = {
                            "status": "halted" if halted else "ok",
                            "rank": r, "world_size": n,
                            "step": view.get("step") if view else None,
                        }
                        body = json.dumps(payload).encode()
                        ctype = "application/json"
                        code = 503 if halted else 200
                    else:
                        body, ctype, code = b"not found\n", "text/plain", 404
                except Exception as e:   # scrape failure is a 500, never a crash
                    body = ("scrape error: %s\n" % e).encode()
                    ctype, code = "text/plain", 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # keep rank stderr clean
                pass

        self._server = ThreadingHTTPServer((self._host, self._port), _Handler)
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="mxt-fleet-metrics", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self):
        return self._port

    @property
    def url(self):
        return "http://%s:%d" % (self._host, self._port)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def prometheus_text():
    """The training rank's scrape body: telemetry counters/gauges/hists
    plus fleet identity gauges, rendered by ``telemetry.promtext``."""
    r, n = world()
    extra = {"fleet.rank": r, "fleet.world_size": n}
    view = last_view()
    if view is not None and view.get("step") is not None:
        extra["fleet.step"] = view["step"]
    with _ring_lock:
        extra["fleet.ring_depth"] = len(_ring)
    return promtext.prometheus_text(extra_gauges=extra)


def metrics_url():
    """URL of the live endpoint, or ``None`` when not serving."""
    ep = _endpoint
    return ep.url if ep is not None else None


# -- lifecycle ----------------------------------------------------------

def enable(stride=None, ring=None, skew_threshold=None, consecutive=None,
           spike_factor=None, regression_factor=None, min_history=None,
           growth_factor=None, on_anomaly=None, halt=None,
           http_port=None):
    """Turn the fleet layer on. ``None`` args fall back to
    ``MXNET_FLEET_*`` env knobs, then module defaults. ``on_anomaly``
    replaces the default one-line stderr warning; ``halt=True`` makes
    anomalies raise :class:`WatchdogHalt` out of ``step_end``;
    ``http_port`` (0 = auto) starts :class:`MetricsEndpoint`."""
    global _enabled, _stride, _ring, _watchdog, _on_anomaly, _halt
    global _endpoint, _atexit_installed
    env = os.environ
    if stride is None:
        stride = int(env.get("MXNET_FLEET_STRIDE", DEFAULT_STRIDE))
    if ring is None:
        ring = int(env.get("MXNET_FLEET_RING", RING_CAPACITY))
    if skew_threshold is None:
        skew_threshold = float(env.get("MXNET_FLEET_SKEW", SKEW_THRESHOLD))
    if consecutive is None:
        consecutive = int(env.get("MXNET_FLEET_WINDOWS", CONSECUTIVE))
    if spike_factor is None:
        spike_factor = SPIKE_FACTOR
    if regression_factor is None:
        regression_factor = REGRESSION_FACTOR
    if min_history is None:
        min_history = MIN_HISTORY
    if growth_factor is None:
        growth_factor = float(env.get("MXNET_FLEET_GROWTH", GROWTH_FACTOR))
    if halt is None:
        halt = env.get("MXNET_FLEET_HALT", "0") == "1"
    with _lock:
        _stride = int(stride)
        _on_anomaly = on_anomaly
        _halt = bool(halt)
        _watchdog = Watchdog(skew_threshold=skew_threshold,
                             consecutive=consecutive,
                             spike_factor=spike_factor,
                             regression_factor=regression_factor,
                             min_history=min_history,
                             growth_factor=growth_factor)
    with _ring_lock:
        if int(ring) != _ring.maxlen:
            _ring = collections.deque(_ring, maxlen=int(ring))
    if not _atexit_installed:
        atexit.register(_atexit_dump)
        _atexit_installed = True
    if http_port is not None and _endpoint is None:
        _endpoint = MetricsEndpoint(http_port).start()
    _enabled = True


def disable():
    """Turn the fleet layer off (ring contents survive for post-mortem
    reads until :func:`clear`)."""
    global _enabled, _endpoint
    _enabled = False
    ep = _endpoint
    _endpoint = None
    if ep is not None:
        try:
            ep.stop()
        except Exception:
            pass


def is_enabled():
    return _enabled


if os.environ.get("MXNET_FLEET", "0") == "1":
    enable()
