"""Capacity observability (r20): lane duty-cycle, saturation, headroom.

The serving stack already answers "how slow was this request" (r12
tracing) and "did the SLO hold" (goodput).  What a control plane needs
is the *leading* question: how close is each replica to the cliff,
before goodput moves.  This module turns stamps the lanes already take
(the r12 retroactive pattern — no new syncs, no new clock reads on the
hot path beyond what tracing established) into four signal families:

* **duty cycle** — per-lane busy/idle interval ledgers.  The prefill
  and decode lanes hand over the ``perf_counter`` stamps they take
  anyway (``lane_busy`` / ``note_tick``); ``utilization`` is the busy
  fraction of a sliding window (default 10 s).
* **occupancy** — decode batch occupancy (active slots ÷ slot
  capacity, EWMA-smoothed per tick) and speculative verify efficiency
  (accepted ÷ drafted tokens), the "is the batch dimension earning its
  keep" dials.
* **KV pressure** — blocks free ÷ total from the paged pool, plus a
  fragmentation trend (EWMA of fragmentation deltas: positive =
  fragmenting, negative = recovering).
* **queue theory** — EWMA arrival-rate (λ, from request inter-arrival
  gaps at ``Replica.offer``) and service-rate (μ) estimators.  μ comes
  from the operational utilization law ``U = X/μ`` → ``μ = X/U``
  (completion throughput ÷ busy fraction, both measured over the SAME
  sliding window — the law only holds on one timescale): the rate the
  replica would sustain at 100 % duty cycle.  ``ρ = λ/μ`` is the saturation measure
  and ``headroom_rps = μ − λ`` the live admission budget —
  ``predicted_max_rate_rps`` (= μ) is the number the offline
  ``benchmark/serving_latency.py`` open-loop sweep measures after the
  fact, available while serving.

A :class:`SaturationWatch` (armed by ``enable()``) runs inside the
note hooks: when a replica's ρ crosses the threshold (default 0.85)
with enough completions behind it, ONE ``{"record": "saturation"}``
JSONL event is emitted, ``capacity.saturation`` is counted, and the
r12 flight recorder is armed via ``tracing.incident("saturation")`` —
*before* queue-wait p99 breaches, which is the point (the event
re-arms after ρ falls back below threshold × 0.8).

The training side mirrors the signal: ``telemetry.fleet`` folds a
duty-cycle float (``compute_ms ÷ step_ms``, the r13 fields) into the
stride exchange — see :func:`duty_cycle` and docs/observability.md.

Cost contract (the telemetry constitution): disabled, every hook is
one module-global boolean test — no lock, no allocation, bounded by
``tests/test_capacity.py``'s 10k-iteration guard; enabled, each hook
is a few float ops under one lock, A/B-gated < 1 % of a decode tick
(``capacity_ab`` in ``SERVING_LATENCY_r20.json``).  Recording never
touches the device.

Environment knobs (read at ``enable()``): ``MXNET_CAPACITY=1``
autostarts with the parent package; ``MXNET_CAPACITY_WINDOW`` (10 s),
``MXNET_CAPACITY_ALPHA`` (0.2), ``MXNET_CAPACITY_RHO`` (0.85),
``MXNET_CAPACITY_MIN_COMPLETIONS`` (8).
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from .. import sanitizer as _sanitizer

__all__ = [
    "enable", "disable", "is_enabled", "reset",
    "EWMA", "RateEstimator", "EventWindow", "IntervalLedger",
    "queue_metrics", "service_rate", "duty_cycle",
    "note_arrival", "note_completion", "note_tick", "note_spec",
    "note_kv", "lane_busy",
    "utilization", "snapshot", "saturated",
]

# -- defaults (env-overridable at enable() time) ------------------------

#: sliding window for busy-fraction accounting, seconds
DEFAULT_WINDOW_S = 10.0
#: EWMA smoothing factor for rates / occupancy / spec efficiency
DEFAULT_ALPHA = 0.2
#: saturation fires when rho crosses this
DEFAULT_RHO_THRESHOLD = 0.85
#: rho must fall below threshold * this factor to re-arm the watch
REARM_FACTOR = 0.8
#: completions a replica needs before its mu estimate is trusted
DEFAULT_MIN_COMPLETIONS = 8
#: mu is not estimated below this busy fraction (the utilization law
#: divides by U; an idle replica's U is noise, not a denominator)
MIN_BUSY_FRACTION = 0.02
#: busy intervals kept per lane ledger (oldest age out)
LEDGER_CAP = 2048

_enabled = False
_lock = _sanitizer.wrap_lock(threading.Lock(), "capacity._lock")
_replicas = {}            # index -> _ReplicaCapacity
_window_s = DEFAULT_WINDOW_S
_alpha = DEFAULT_ALPHA
_rho_threshold = DEFAULT_RHO_THRESHOLD
_min_completions = DEFAULT_MIN_COMPLETIONS


def _telemetry():
    # resolved lazily; the parent package imports this module
    return sys.modules.get("mxnet_tpu.telemetry")


# -- pure estimator pieces (unit-tested without the serving stack) ------

class EWMA:
    """Exponentially-weighted moving average; ``None`` until fed."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha=DEFAULT_ALPHA):
        self.alpha = float(alpha)
        self.value = None

    def update(self, x):
        x = float(x)
        if self.value is None:
            self.value = x
        else:
            self.value = self.alpha * x + (1.0 - self.alpha) * self.value
        return self.value


class RateEstimator:
    """Events/second from EWMA-smoothed inter-event gaps.

    Pure: the caller supplies every timestamp, so tests drive it with
    synthetic clocks.  ``rate`` is ``None`` until two events arrive;
    a long silence decays the estimate through :meth:`rate_at` (the
    open gap since the last event counts as a sample floor, so a
    stopped arrival stream reads as a falling λ, not a frozen one).
    """

    __slots__ = ("_gap", "_last", "count")

    def __init__(self, alpha=DEFAULT_ALPHA):
        self._gap = EWMA(alpha)
        self._last = None
        self.count = 0

    def observe(self, t):
        t = float(t)
        if self._last is not None and t > self._last:
            self._gap.update(t - self._last)
        self._last = t
        self.count += 1

    @property
    def rate(self):
        g = self._gap.value
        return (1.0 / g) if g else None

    def rate_at(self, now):
        """Rate estimate at ``now``: if the open gap since the last
        event already exceeds the smoothed gap, it bounds the rate."""
        g = self._gap.value
        if g is None:
            return None
        if self._last is not None and now - self._last > g:
            g = self._gap.alpha * (now - self._last) \
                + (1.0 - self._gap.alpha) * g
        return 1.0 / g if g > 0 else None


class EventWindow:
    """Events/second over the same sliding window the interval
    ledgers use: timestamps in a bounded ring, rate = count ÷ span
    (ramp-up aware).  μ divides a throughput by a busy fraction — the
    operational law ``U = X/μ`` only holds when X and U are measured
    over the SAME period, so the completion rate must be windowed like
    the utilization, not EWMA-smoothed like λ (an EWMA X right after
    an idle gap reads "recent burst pace" against a window-diluted U
    and inflates μ several-fold)."""

    __slots__ = ("window_s", "_cap", "_times", "_opened", "count")

    def __init__(self, window_s=DEFAULT_WINDOW_S, cap=LEDGER_CAP):
        self.window_s = float(window_s)
        self._cap = int(cap)
        self._times = deque()
        self._opened = None
        self.count = 0

    def observe(self, t):
        t = float(t)
        if self._opened is None:
            self._opened = t
        self._times.append(t)
        self.count += 1
        self._prune(t - self.window_s)

    def _prune(self, lo):
        # hot-path discipline: expired timestamps leave as they expire,
        # so no call ever scans the window (amortized O(1) — each event
        # is appended once and popped once)
        times = self._times
        while times and times[0] <= lo:
            times.popleft()
        while len(times) > self._cap:
            times.popleft()

    def rate(self, now):
        """Events/sec over ``[now - window, now]``; ``None`` before
        the first event, 0.0 for a gone-quiet stream.  Queries must be
        monotone in ``now`` (expired events are dropped for O(1) cost)
        — true for wall-clock callers by construction."""
        if self._opened is None:
            return None
        self._prune(now - self.window_s)
        span = min(self.window_s, max(now - self._opened, 1e-9))
        times = self._times
        n = len(times)
        if n and times[-1] > now:
            n = sum(1 for t in times if t <= now)
        return n / span


class IntervalLedger:
    """Bounded ring of busy ``(t0, t1)`` intervals → busy fraction
    over a sliding window.  Intervals are appended retroactively from
    stamps the caller already took; nothing here reads a clock."""

    __slots__ = ("window_s", "_cap", "_intervals", "_opened", "_busy")

    def __init__(self, window_s=DEFAULT_WINDOW_S, cap=LEDGER_CAP):
        self.window_s = float(window_s)
        self._cap = int(cap)
        self._intervals = deque()
        self._opened = None     # first t0 ever seen: ramp-up horizon
        self._busy = 0.0        # running sum over retained intervals

    def add(self, t0, t1):
        if t1 <= t0:
            return
        if self._opened is None:
            self._opened = t0
        self._intervals.append((t0, t1))
        self._busy += t1 - t0
        self._prune(t1 - self.window_s)

    def _prune(self, lo):
        # amortized O(1): each interval enters and leaves the running
        # sum exactly once, so utilization never scans the window
        iv = self._intervals
        while iv and iv[0][1] <= lo:
            a, b = iv.popleft()
            self._busy -= b - a
        while len(iv) > self._cap:
            a, b = iv.popleft()
            self._busy -= b - a

    def utilization(self, now):
        """Busy fraction of ``[now - window, now]``; the denominator
        ramps from first observation so a 1 s-old ledger reports its
        1 s truth instead of diluting into an empty 10 s window.
        Queries must be monotone in ``now`` (expired intervals are
        dropped) — true for wall-clock callers by construction."""
        if self._opened is None:
            return 0.0
        lo = now - self.window_s
        span = min(self.window_s, max(now - self._opened, 1e-9))
        self._prune(lo)
        busy = self._busy
        iv = self._intervals
        if iv:
            # at most the oldest retained interval straddles the window
            # start (a lane's intervals are sequential), and at most
            # the newest runs past ``now``: clamp both, scan neither
            a0, b0 = iv[0]
            if a0 < lo:
                busy -= lo - a0
            an, bn = iv[-1]
            if bn > now > an:
                busy -= bn - now
        return max(0.0, min(1.0, busy / span))


def queue_metrics(lam, mu):
    """``(rho, headroom_rps)`` from arrival and service rates; either
    input ``None``/non-positive → ``(None, None)``."""
    if not lam or not mu or lam <= 0 or mu <= 0:
        return (None, None)
    return (lam / mu, max(0.0, mu - lam))


def service_rate(completion_rate, busy_fraction,
                 floor=MIN_BUSY_FRACTION):
    """μ via the operational utilization law ``U = X/μ`` → ``μ = X/U``
    (completion throughput ÷ busy fraction): what the replica would
    complete at 100 % duty cycle.  ``None`` until the replica has been
    measurably busy (below ``floor`` the denominator is noise)."""
    if completion_rate is None or busy_fraction is None:
        return None
    if completion_rate <= 0 or busy_fraction < floor:
        return None
    return completion_rate / min(1.0, busy_fraction)


def duty_cycle(compute_ms, step_ms):
    """Training-side duty cycle, ``compute_ms ÷ step_ms`` clamped to
    [0, 1] — the float ``telemetry.fleet`` folds into the stride
    exchange (0.0 when the step time is unknown)."""
    try:
        s = float(step_ms)
        c = float(compute_ms)
    except (TypeError, ValueError):
        return 0.0
    if s <= 0:
        return 0.0
    return max(0.0, min(1.0, c / s))


# -- per-replica accounting ---------------------------------------------

class _ReplicaCapacity:
    __slots__ = ("index", "lanes", "arrivals", "completions",
                 "occupancy", "slot_capacity", "spec_drafted",
                 "spec_accepted", "kv_free", "kv_total",
                 "kv_fragmentation", "kv_frag_trend", "saturated",
                 "saturation_events")

    def __init__(self, index, window_s, alpha):
        self.index = index
        self.lanes = {"prefill": IntervalLedger(window_s),
                      "decode": IntervalLedger(window_s)}
        self.arrivals = RateEstimator(alpha)
        self.completions = EventWindow(window_s)
        self.occupancy = EWMA(alpha)
        self.slot_capacity = None
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.kv_free = None
        self.kv_total = None
        self.kv_fragmentation = EWMA(alpha)
        self.kv_frag_trend = EWMA(alpha)   # EWMA of frag deltas
        self.saturated = False
        self.saturation_events = 0

    def lane(self, name):
        led = self.lanes.get(name)
        if led is None:
            led = self.lanes[name] = IntervalLedger(
                self.lanes["decode"].window_s)
        return led

    def rates(self, now):
        """(lambda, X, mu) at ``now`` — arrival rate, completion
        throughput, and the utilization-law service rate."""
        lam = self.arrivals.rate_at(now)
        x = self.completions.rate(now)
        busy = self.lanes["decode"].utilization(now)
        # prefill-only traffic (max_new_tokens == 1) never ticks the
        # decode lane; fold both lanes so mu reflects the server's
        # actual busy fraction, capped at 1.
        busy = min(1.0, busy + self.lanes["prefill"].utilization(now))
        return lam, x, service_rate(x, busy)

    def view(self, now):
        lam, x, mu = self.rates(now)
        rho, headroom = queue_metrics(lam, mu)
        spec_eff = (self.spec_accepted / self.spec_drafted
                    if self.spec_drafted else None)
        kv_free_frac = (self.kv_free / self.kv_total
                        if self.kv_total else None)
        return {
            "replica": self.index,
            "utilization": round(
                self.lanes["decode"].utilization(now), 6),
            "prefill_utilization": round(
                self.lanes["prefill"].utilization(now), 6),
            "occupancy": self.occupancy.value,
            "slot_capacity": self.slot_capacity,
            "spec_efficiency": spec_eff,
            "kv_free_frac": kv_free_frac,
            "kv_fragmentation": self.kv_fragmentation.value,
            "kv_fragmentation_trend": self.kv_frag_trend.value,
            "arrival_rate_rps": lam,
            "completion_rate_rps": x,
            "service_rate_rps": mu,
            "predicted_max_rate_rps": mu,
            "rho": rho,
            "headroom_rps": headroom,
            "completions": self.completions.count,
            "saturated": self.saturated,
            "saturation_events": self.saturation_events,
        }


def _replica(index):
    rc = _replicas.get(index)
    if rc is None:
        rc = _replicas[index] = _ReplicaCapacity(
            index, _window_s, _alpha)
    return rc


# -- the saturation watch ------------------------------------------------

def _check_saturation(rc, now):
    """Edge-triggered under ``_lock``: returns the event record to emit
    (the caller emits it after releasing the lock — telemetry and
    tracing take their own locks) or ``None``."""
    if rc.completions.count < _min_completions:
        return None
    lam, x, mu = rc.rates(now)
    rho, headroom = queue_metrics(lam, mu)
    if rho is None:
        return None
    if rc.saturated:
        if rho < _rho_threshold * REARM_FACTOR:
            rc.saturated = False
        return None
    if rho < _rho_threshold:
        return None
    rc.saturated = True
    rc.saturation_events += 1
    return {
        "record": "saturation",
        "replica": rc.index,
        "wall_time": time.time(),
        "rho": round(rho, 4),
        "threshold": _rho_threshold,
        "arrival_rate_rps": round(lam, 3),
        "service_rate_rps": round(mu, 3),
        "headroom_rps": round(headroom, 3),
        "utilization": round(
            rc.lanes["decode"].utilization(now), 6),
        "occupancy": rc.occupancy.value,
        "kv_free_frac": (rc.kv_free / rc.kv_total
                         if rc.kv_total else None),
        "completions": rc.completions.count,
    }


def _emit_saturation(event):
    tel = _telemetry()
    if tel is not None and tel.is_enabled():
        tel.count("capacity.saturation")
        tel.count(f"capacity.saturation|replica={event['replica']}")
        tel.emit(event)
    # arm the r12 flight recorder BEFORE goodput degrades: the ring
    # holds the traces leading up to the crossing
    try:
        from . import tracing
        tracing.incident("saturation",
                         context={k: event[k] for k in
                                  ("replica", "rho", "headroom_rps",
                                   "arrival_rate_rps",
                                   "service_rate_rps")})
    except Exception:
        pass    # the watch never raises into a lane thread


# -- hot-path hooks (one boolean when disabled) --------------------------

def note_arrival(index, t=None):
    """A request entered replica ``index``'s queue (called from
    ``Replica.offer`` on accepted offers only — rejects never arrive)."""
    if not _enabled:
        return
    now = time.perf_counter() if t is None else t
    with _lock:
        rc = _replica(index)
        rc.arrivals.observe(now)
        event = _check_saturation(rc, now)
    if event is not None:
        _emit_saturation(event)


def note_completion(index, t=None):
    """A request finished on replica ``index`` (``Replica.finish``)."""
    if not _enabled:
        return
    now = time.perf_counter() if t is None else t
    with _lock:
        rc = _replica(index)
        rc.completions.observe(now)
        event = _check_saturation(rc, now)
    if event is not None:
        _emit_saturation(event)


def note_tick(index, active, slot_capacity, t0, t1):
    """One decode tick: ``active`` slots of ``slot_capacity`` were
    advanced between the stamps the lane already took."""
    if not _enabled:
        return
    with _lock:
        rc = _replica(index)
        rc.lanes["decode"].add(t0, t1)
        rc.slot_capacity = int(slot_capacity)
        if slot_capacity:
            rc.occupancy.update(active / slot_capacity)


def note_spec(index, drafted, accepted):
    """Speculative verify outcome for one tick (token totals)."""
    if not _enabled:
        return
    with _lock:
        rc = _replica(index)
        rc.spec_drafted += int(drafted)
        rc.spec_accepted += int(accepted)


def note_kv(index, free_blocks, total_blocks, fragmentation=None):
    """Paged-pool pressure.  ``fragmentation`` rides along where the
    caller already computed ``mgr.stats()`` (the summary path); the
    per-tick caller passes only the allocator's free/total counters."""
    if not _enabled:
        return
    with _lock:
        rc = _replica(index)
        rc.kv_free = int(free_blocks)
        rc.kv_total = int(total_blocks)
        if fragmentation is not None:
            prev = rc.kv_fragmentation.value
            cur = rc.kv_fragmentation.update(fragmentation)
            if prev is not None:
                rc.kv_frag_trend.update(cur - prev)


def lane_busy(index, lane, t0, t1):
    """Record a retroactive busy interval for ``lane`` (``"prefill"``
    forwards hand over their existing ``t_start``/``t_first`` stamps)."""
    if not _enabled:
        return
    with _lock:
        _replica(index).lane(lane).add(t0, t1)


# -- queries -------------------------------------------------------------

def utilization(index, lane="decode", now=None):
    """Busy fraction of ``lane`` on replica ``index`` over the sliding
    window; 0.0 when disabled or unseen."""
    if not _enabled:
        return 0.0
    if now is None:
        now = time.perf_counter()
    with _lock:
        rc = _replicas.get(index)
        if rc is None:
            return 0.0
        led = rc.lanes.get(lane)
        return led.utilization(now) if led is not None else 0.0


def saturated(index=None):
    """Whether ``index`` (or, with ``None``, any replica) currently
    sits above the ρ threshold."""
    if not _enabled:
        return False
    with _lock:
        if index is not None:
            rc = _replicas.get(index)
            return bool(rc is not None and rc.saturated)
        return any(rc.saturated for rc in _replicas.values())


def snapshot(index=None, now=None):
    """Capacity view: one dict for replica ``index``, or
    ``{index: view}`` for every tracked replica.  ``{}``/``None`` when
    disabled — the serving surfaces skip the block entirely."""
    if not _enabled:
        return None if index is not None else {}
    if now is None:
        now = time.perf_counter()
    with _lock:
        if index is not None:
            rc = _replicas.get(index)
            return rc.view(now) if rc is not None else None
        return {i: rc.view(now) for i, rc in _replicas.items()}


# -- lifecycle -----------------------------------------------------------

def enable(window_s=None, alpha=None, rho_threshold=None,
           min_completions=None):
    """Arm capacity accounting (idempotent).  Usually reached through
    ``telemetry.enable(capacity=True)`` or ``MXNET_CAPACITY=1``."""
    global _enabled, _window_s, _alpha, _rho_threshold, _min_completions
    env = os.environ.get
    _window_s = float(window_s if window_s is not None
                      else env("MXNET_CAPACITY_WINDOW",
                               DEFAULT_WINDOW_S))
    _alpha = float(alpha if alpha is not None
                   else env("MXNET_CAPACITY_ALPHA", DEFAULT_ALPHA))
    _rho_threshold = float(
        rho_threshold if rho_threshold is not None
        else env("MXNET_CAPACITY_RHO", DEFAULT_RHO_THRESHOLD))
    _min_completions = int(
        min_completions if min_completions is not None
        else env("MXNET_CAPACITY_MIN_COMPLETIONS",
                 DEFAULT_MIN_COMPLETIONS))
    with _lock:
        _replicas.clear()
    _enabled = True


def disable():
    global _enabled
    _enabled = False
    with _lock:
        _replicas.clear()


def is_enabled():
    return _enabled


def reset():
    """Forget every replica's ledgers/estimators (keeps the switch)."""
    with _lock:
        _replicas.clear()


if os.environ.get("MXNET_CAPACITY", "0") == "1":
    enable()
