"""Runtime recompile sanitizer: post-warmup retrace detection with
structural signature diffing.

The compile-once invariant (every hot path traces+compiles once per
signature and replays forever) is enforced statically by mxlint
T13–T15; this module is the runtime twin.  Every registered compile
site — CachedOp forward/backward, bulked engine segments,
FusedTrainStep, the trainer's fused update, the predictor, serving
prefill/decode — calls :func:`observe` from its cache-MISS branch only
(replays never reach it), passing a dict of *named* signature
components.  After a declared warmup (:func:`warm`, or N steps via
``warmup_steps``), a second-or-later signature at the same site is a
**retrace**: it is attributed to its Python call site, structurally
diffed against the nearest prior signature at that site — naming
exactly which aval shape/dtype/weak-type, closure attribute, mesh or
numerics/remat mode diverged — and then warns or raises
:class:`RetraceError` per mode.  A first-ever signature at a site is a
new program, not a retrace, even post-warmup.

Null path: one module-global boolean (``_enabled``) read at each
site's miss branch; disabled cost is one attribute load on a branch
that is already rare by construction.

Env wiring: ``MXNET_SANITIZE_RETRACE=1|warn`` observes and warns,
``=raise`` raises; ``MXNET_SANITIZE_RETRACE_WARMUP=N`` declares an
N-step warmup counted at ``telemetry.step_end`` boundaries (requires
telemetry step scopes; :func:`warm` is the explicit alternative).

Every new compile (baseline or violation) lands as a
``{"record": "retrace", ...}`` line on the telemetry JSONL sink when
one is attached; violations additionally feed the fleet flight
recorder.  ``tools/retrace_report.py`` renders per-site signature
timelines and human diffs from those records.
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
import warnings

__all__ = ["RetraceError", "enable", "disable", "reset", "warm",
           "is_warm", "is_enabled", "on_step", "observe", "violations",
           "sites", "diff_components", "cachedop_components"]

#: per-site signature histories are bounded — a runaway retrace loop
#: must not turn the sanitizer into a leak
_MAX_HISTORY = 64
_MAX_VIOLATIONS = 256


class RetraceError(RuntimeError):
    """A registered compile site re-traced after warmup.  The message
    names the site, the Python call site that triggered the compile and
    the exact signature components that diverged from the nearest prior
    signature."""


def _env_mode():
    v = os.environ.get("MXNET_SANITIZE_RETRACE", "").strip().lower()
    if v in ("", "0", "off", "false", "no"):
        return None
    return "raise" if v == "raise" else "warn"


def _env_warmup():
    v = os.environ.get("MXNET_SANITIZE_RETRACE_WARMUP", "").strip()
    try:
        return int(v) if v else None
    except ValueError:
        return None


_lock = threading.Lock()
_mode = _env_mode()
_enabled = _mode is not None
_warmup_steps = _env_warmup()
_warmed = False
_steps_seen = 0
_sites = {}        # (kind, instance) -> {"site": str, "history": [entry]}
_violations = []


# -- lifecycle ---------------------------------------------------------------

def enable(mode="warn", warmup_steps=None):
    """Switch the sanitizer on.  ``mode`` is ``"warn"`` (RuntimeWarning
    per post-warmup retrace) or ``"raise"`` (RetraceError).
    ``warmup_steps`` declares an N-step warmup counted at telemetry
    step boundaries; None keeps warmup explicit via :func:`warm`."""
    global _enabled, _mode, _warmup_steps
    if mode not in ("warn", "raise"):
        raise ValueError(f"mode must be 'warn' or 'raise', got {mode!r}")
    with _lock:
        _mode = mode
        _warmup_steps = warmup_steps
        _enabled = True


def disable():
    global _enabled
    _enabled = False


def is_enabled():
    return _enabled


def reset():
    """Forget every observed signature, violation and warmup state (the
    enabled/mode flags survive — tests flip those via enable/disable)."""
    global _warmed, _steps_seen
    with _lock:
        _sites.clear()
        _violations.clear()
        _warmed = False
        _steps_seen = 0


def warm():
    """Declare warmup over: from here on, a second-or-later signature
    at any registered site is a retrace violation."""
    global _warmed
    _warmed = True


def is_warm():
    return _warmed


def on_step():
    """Telemetry step-boundary hook (called from ``step_end`` while the
    sanitizer is enabled): counts steps toward a declared
    ``warmup_steps`` warmup."""
    global _steps_seen, _warmed
    with _lock:
        _steps_seen += 1
        if _warmup_steps is not None and not _warmed and \
                _steps_seen >= _warmup_steps:
            _warmed = True


def violations():
    """Post-warmup retrace records observed so far (list of dicts with
    ``site``/``where``/``diff``/``step`` keys) — the test hook."""
    with _lock:
        return list(_violations)


def sites():
    """Snapshot: {(kind, instance): signature count} for every
    registered site that has compiled at least once."""
    with _lock:
        return {k: len(v["history"]) for k, v in _sites.items()}


# -- signature plumbing ------------------------------------------------------

def _canon(value):
    """Hashable, comparison-stable form: lists become tuples (JSONL
    round-trips arrive as lists), dicts become sorted item tuples."""
    if isinstance(value, (list, tuple)):
        return tuple(_canon(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((str(k), _canon(v)) for k, v in value.items()))
    return value


def _jsonable(value):
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return repr(value)


def _is_aval(x):
    """(shape-tuple, dtype-str[, weak-bool]) — the aval spelling every
    compile signature in this tree uses."""
    return (isinstance(x, tuple) and len(x) in (2, 3) and
            isinstance(x[0], tuple) and isinstance(x[1], str) and
            (len(x) == 2 or isinstance(x[2], bool)))


_AVAL_FIELDS = ("shape", "dtype", "weak_type")


def diff_components(old, new):
    """Structural diff of two component dicts: a list of human strings,
    one per diverging leaf, naming the exact path — e.g.
    ``args[1].shape: (8, 16) -> (8, 32)`` or
    ``rescale_grad: 1.0 -> 0.5``."""
    return _diff_dicts(old, new)


def _diff_value(path, a, b, out):
    if a == b:
        return
    if isinstance(a, tuple) and isinstance(b, tuple):
        if _is_aval(a) and _is_aval(b):
            for name, x, y in zip(_AVAL_FIELDS, a, b):
                if x != y:
                    out.append(f"{path}.{name}: {x!r} -> {y!r}"
                               if path else f"{name}: {x!r} -> {y!r}")
            if len(a) != len(b):
                out.append(f"{path}: {a!r} -> {b!r}")
            return
        if len(a) == len(b):
            for i, (x, y) in enumerate(zip(a, b)):
                _diff_value(f"{path}[{i}]", x, y, out)
            return
        out.append(f"{path}: length {len(a)} -> {len(b)} "
                   f"({a!r} -> {b!r})")
        return
    out.append(f"{path}: {a!r} -> {b!r}" if path else f"{a!r} -> {b!r}")


def _diff_dicts(old, new):
    out = []
    for key in sorted(set(old) | set(new)):
        if key not in old:
            out.append(f"{key}: <absent> -> {_canon(new[key])!r}")
        elif key not in new:
            out.append(f"{key}: {_canon(old[key])!r} -> <absent>")
        else:
            _diff_value(key, _canon(old[key]), _canon(new[key]), out)
    return out


def cachedop_components(sig):
    """Decompose a CachedOp compile key (gluon/block.py layout:
    ``(arg avals, training, platform, param avals, mesh, numerics)``)
    into named components for the differ."""
    if isinstance(sig, tuple) and len(sig) == 6:
        return {"args": sig[0], "training": sig[1], "platform": sig[2],
                "params": sig[3], "mesh": sig[4], "numerics": sig[5]}
    return {"signature": sig}


def _caller():
    """First stack frame outside mxnet_tpu — the Python call site this
    compile is attributed to.  Falls back to the innermost
    non-telemetry frame (worker threads dispatch from inside the
    runtime)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fallback = None
    for fr in reversed(traceback.extract_stack()):
        fn = os.path.abspath(fr.filename)
        if fn.startswith(os.path.dirname(os.path.abspath(__file__))):
            continue  # this module / telemetry siblings
        where = "%s:%d in %s" % (
            os.path.relpath(fn, os.getcwd()) if fn.startswith(os.getcwd())
            else os.path.basename(fn), fr.lineno, fr.name)
        if fallback is None:
            fallback = where
        if not fn.startswith(pkg_root):
            return where
    return fallback or "<unknown>"


# -- the observe hook --------------------------------------------------------

def observe(kind, instance, components, site=None):
    """Record one compile at a registered site.  Call ONLY from the
    site's cache-miss branch, behind ``if _retrace._enabled:``.

    ``kind`` is the costs-registry kind string ("cachedop",
    "step_fusion", "trainer_fused", ...), ``instance`` discriminates
    live objects sharing the kind (``id(self)``), ``components`` is a
    dict of named, hashable signature parts and ``site`` the
    module-qualified compile-site identity
    ("mxnet_tpu.gluon.trainer:Trainer._try_fused_update").

    Baseline compiles (pre-warmup, or the first signature a site ever
    sees) are recorded silently; a post-warmup second-or-later
    signature is a violation: warn or raise per mode."""
    if not _enabled:
        return None
    comps = {str(k): _canon(v) for k, v in components.items()}
    where = _caller()
    key = (kind, instance)
    with _lock:
        entry = _sites.get(key)
        if entry is None:
            entry = _sites[key] = {"site": site or kind, "history": []}
        history = entry["history"]
        for prior in history:
            if prior["components"] == comps:
                return None  # replay raced a concurrent miss: not new
        rec = {"components": comps, "where": where, "step": _steps_seen,
               "warm": _warmed}
        if len(history) >= _MAX_HISTORY:
            del history[0]
        history.append(rec)
        is_violation = _warmed and len(history) > 1
        diff = against = None
        if is_violation:
            candidates = [(len(_diff_dicts(p["components"], comps)), i, p)
                          for i, p in enumerate(history[:-1])]
            _, idx, nearest = min(candidates, key=lambda t: (t[0], -t[1]))
            diff = _diff_dicts(nearest["components"], comps)
            against = {"signature_index": idx, "where": nearest["where"],
                       "step": nearest["step"]}
            violation = {
                "site": entry["site"], "kind": kind, "instance": instance,
                "where": where, "step": _steps_seen, "diff": diff,
                "against": against,
                "signature_index": len(history) - 1,
            }
            if len(_violations) < _MAX_VIOLATIONS:
                _violations.append(violation)
        mode = _mode
        sig_index = len(history) - 1
    action = ("raise" if mode == "raise" else "warn") if is_violation \
        else "baseline"
    _emit_record(action, kind, instance, entry["site"], where, comps,
                 sig_index, diff, against)
    if not is_violation:
        return None
    msg = ("retrace at %s (signature #%d, compiled from %s): "
           "diverged from signature #%d [%s] in: %s"
           % (entry["site"], sig_index, where, against["signature_index"],
              against["where"], "; ".join(diff) or "<structurally equal>"))
    if mode == "raise":
        raise RetraceError(msg)
    warnings.warn(msg, RuntimeWarning, stacklevel=2)
    return msg


def _emit_record(action, kind, instance, site, where, comps, sig_index,
                 diff, against):
    """One ``retrace`` JSONL record per new compile + a flight-recorder
    entry per violation.  Never raises — observability is best-effort."""
    try:
        telemetry = sys.modules.get("mxnet_tpu.telemetry")
        if telemetry is None:
            return
        rec = {"record": "retrace", "action": action, "site": site,
               "kind": kind, "instance": instance, "where": where,
               "step": _steps_seen, "signature_index": sig_index,
               "components": _jsonable(comps)}
        if diff is not None:
            rec["diff"] = list(diff)
            rec["against"] = dict(against)
        telemetry.emit(rec)
        if action != "baseline" and telemetry.fleet._enabled:
            telemetry.fleet.incident("retrace", context=rec)
    except Exception:
        pass
