"""In-compile tensor-statistics tier: layer-resolved numerics telemetry.

Every other telemetry tier here watches the step from the outside —
spans, counters, memory ledgers.  This one watches the *inside* of the
compiled step: per-layer / per-param l2 norm, max-abs, mean, and nan/inf
counts, computed as part of the step's own XLA program and returned as a
small side-output tree.  No ``jax.debug``, no per-tensor host syncs —
the stats ride the step outputs as device scalars and cross to the host
in ONE ``jax.device_get`` every ``stride`` steps.

The tier honors the house telemetry contract:

* **one-boolean disabled path** — ``tap()`` is a single ``if not
  _enabled: return`` when off; nothing allocates, nothing locks.
* **compile-once** — enabling/disabling numerics changes the compile
  signature (``signature()`` is a key in every step cache), so each mode
  keeps exactly one signature and toggling never poisons a cache.
* **never raises into training** — a failed stat drops that stat, not
  the step.
* **host work only at the stride boundary** — non-stride steps drop
  their pending device stats without a sync.

Three layers of machinery live here:

1. *Taps* (``tap``/``tap_stacked``/``stats_of``): called from model and
   trainer code.  Inside a trace a tap appends to the active
   ``collecting()`` scope so the stats become jit outputs; eagerly it
   queues device scalars directly.
2. *Harvest* (``step_summary``): called from ``telemetry.step_end`` —
   materializes the pending stats at the stride, derives ``first_nan``
   provenance (path + layer) and an aggregate ``grad_norm``, and mirrors
   into live profiler counter tracks.
3. *Forensics* (``capture_step``/``bisect``): snapshot a flagged step's
   (inputs, params, rng) through the async checkpointer, then replay it
   eagerly with a per-op NaN bisection hook to name the first failing
   op.  Replay is the one place host syncs are the point.
"""

import json
import os
import threading
import time
from contextlib import contextmanager

import sys

__all__ = [
    "enable", "disable", "is_enabled", "clear", "signature",
    "stats_of", "tap", "tap_stacked", "collecting",
    "record_compiled", "record_stacked", "step_summary", "consume",
    "arm_capture", "capture_step", "load_capture", "bisect",
    "layer_of", "DEFAULT_STRIDE",
]

DEFAULT_STRIDE = 16
#: pending-entry cap — bounds device-scalar queue growth if step_summary
#: is never drained (e.g. numerics on, telemetry off)
PENDING_CAP = 4096

_enabled = False
_stride = DEFAULT_STRIDE
_step_seq = 0          # fallback step counter when records carry none
_pending = []          # [(path, stats, stacked?)] — device-side until stride
_lock = threading.Lock()
_tls = threading.local()

_capture_dir = None
_capture_armed = False


# --- enable / disable --------------------------------------------------------

def enable(stride=None, capture_dir=None):
    """Turn the tier on.  ``stride``: materialize/emit every N steps
    (env ``MXNET_NUMERICS_STRIDE``, default 16).  ``capture_dir``: arm
    the divergence capture hook (see :func:`arm_capture`).

    Taps compiled while the tier was off stay off for those traces —
    ``signature()`` participates in the step compile keys, so the next
    dispatch retraces with stats baked in (one signature per mode)."""
    global _enabled, _stride
    if stride is None:
        stride = int(os.environ.get("MXNET_NUMERICS_STRIDE", DEFAULT_STRIDE))
    _stride = max(1, int(stride))
    if capture_dir:
        arm_capture(capture_dir)
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def is_enabled():
    return _enabled


def clear():
    """Reset all state (tests).  Leaves the tier disabled."""
    global _enabled, _stride, _step_seq, _capture_dir, _capture_armed
    _enabled = False
    _stride = DEFAULT_STRIDE
    _step_seq = 0
    _capture_dir = None
    _capture_armed = False
    with _lock:
        del _pending[:]
    _tls.stack = []


def signature():
    """Compile-signature token: every step cache (CachedOp, fused step,
    fused trainer update, serving engines) keys on this so stats-on and
    stats-off each keep exactly one signature."""
    return _enabled


#: alias with trace-time-snapshot semantics spelled out: call at graph
#: *build* time and bake the result into the trace's static structure
trace_enabled = is_enabled


# --- stats -------------------------------------------------------------------

def stats_of(raw):
    """Per-tensor stat bundle as device scalars: ``{"l2", "maxabs",
    "mean"}`` float32, ``{"nan", "inf"}`` int32.  Pure jnp math — safe
    under trace, safe eagerly; no host transfer happens here."""
    import jax.numpy as jnp

    x = raw if hasattr(raw, "dtype") else jnp.asarray(raw)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        xf = jnp.abs(x).astype(jnp.float32)
        nan = jnp.sum(jnp.isnan(x)).astype(jnp.int32)
        inf = jnp.sum(jnp.isinf(x)).astype(jnp.int32)
    elif jnp.issubdtype(x.dtype, jnp.floating):
        xf = x.astype(jnp.float32)
        nan = jnp.sum(jnp.isnan(x)).astype(jnp.int32)
        inf = jnp.sum(jnp.isinf(x)).astype(jnp.int32)
    else:  # int/bool tensors can't hold nan/inf
        xf = x.astype(jnp.float32)
        nan = jnp.zeros((), jnp.int32)
        inf = jnp.zeros((), jnp.int32)
    zero = jnp.zeros((), jnp.float32)
    has = bool(x.size)  # static shape — fine at trace time
    return {
        "l2": jnp.sqrt(jnp.sum(xf * xf)) if has else zero,
        "maxabs": jnp.max(jnp.abs(xf)) if has else zero,
        "mean": jnp.mean(xf) if has else zero,
        "nan": nan,
        "inf": inf,
    }


def layer_of(path):
    """First integer component of a dotted stat path, or -1.
    ``decoder.7.ffn`` → 7; ``grad.decoder.3.attn.wq`` → 3."""
    for part in str(path).split("."):
        if part.isdigit():
            return int(part)
    return -1


# --- collector (trace scope) -------------------------------------------------

class _Collector:
    """Accumulates taps fired while a traced function runs.  ``names``
    is host-side static metadata (saved as a trace side effect, like
    CachedOp's ``struct``); ``stats`` is the device/tracer half that
    must leave the trace as jit outputs."""

    __slots__ = ("names", "stats")

    def __init__(self):
        self.names = []
        self.stats = []

    def drain(self):
        """Return ``(names, stats_tuple)`` — the stats tuple is a plain
        pytree (tuple of dicts of scalars), safe to return from jit."""
        names, stats = self.names, tuple(self.stats)
        self.names, self.stats = [], []
        return names, stats


@contextmanager
def collecting():
    """Scope a traced region so taps inside it land on a collector
    instead of the eager queue.  Re-entrant; innermost scope wins."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    col = _Collector()
    stack.append(col)
    try:
        yield col
    finally:
        stack.pop()


def _active_collector():
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _is_tracer(raw):
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(raw, jax.core.Tracer)


def _push(entry):
    with _lock:
        if len(_pending) < PENDING_CAP:
            _pending.append(entry)


# --- taps --------------------------------------------------------------------

def tap(name, x):
    """Record stats for one tensor.  ``x``: NDArray or raw array.
    Disabled path is one boolean test.  Inside an active
    :func:`collecting` scope the stats become trace outputs; eagerly
    they queue as device scalars.  A tracer seen with no collector is
    dropped (stats could not leave that trace without leaking)."""
    if not _enabled:
        return
    raw = getattr(x, "_data", x)
    if raw is None:
        return
    col = _active_collector()
    try:
        if col is not None:
            st = stats_of(raw)
            col.names.append(str(name))
            col.stats.append(st)
        elif not _is_tracer(raw):
            _push((str(name), stats_of(raw), False))
    except Exception:  # never raises into training
        pass


def tap_stacked(name, stats):
    """Record a stacked stat bundle — each value shaped ``(L, ...)``
    with leading layer axis (the scanned-decoder path).  ``stats`` is a
    dict with the :func:`stats_of` keys."""
    if not _enabled:
        return
    col = _active_collector()
    try:
        if col is not None:
            col.names.append("+" + str(name))  # '+' marks stacked
            col.stats.append(dict(stats))
        elif not any(_is_tracer(v) for v in stats.values()):
            _push((str(name), dict(stats), True))
    except Exception:
        pass


def record_compiled(names, stats):
    """Queue stats that exited a compiled call as side outputs.
    ``names`` from the trace-time collector, ``stats`` the matching
    jit-output tuple.  Names prefixed ``+`` (see :func:`tap_stacked`)
    re-enter as stacked entries.

    When an *outer* collector is active (a compiled graph dispatched
    inside a bigger trace) the entries forward to it — they must leave
    the outer compile as its side outputs.  Tracer stats with no outer
    collector are dropped: queuing them would leak the trace."""
    if not _enabled or not names:
        return
    col = _active_collector()
    if col is not None:
        for n, s in zip(names, stats):
            col.names.append(n)
            col.stats.append(s)
        return
    for n, s in zip(names, stats):
        leaves = s.values() if isinstance(s, dict) else (s,)
        if any(_is_tracer(v) for v in leaves):
            continue
        if n.startswith("+"):
            _push((n[1:], s, True))
        else:
            _push((n, s, False))


def record_stacked(name, stats):
    """Queue one stacked entry directly (already concrete or device)."""
    if not _enabled:
        return
    _push((str(name), dict(stats), True))


# --- harvest -----------------------------------------------------------------

def _materialize(entries):
    """The ONE host sync of the tier: fetch every pending device stat in
    a single transfer.  Name is deliberate — mxlint's MATERIALIZE_DEFS
    sanctions this def as an intentional exchange boundary."""
    import jax
    return jax.device_get([e[1] for e in entries])  # mxlint: allow=T1


def _expand(entries, fetched):
    """(path, stats, stacked) × host values → ordered {path: stats}
    with stacked entries fanned out to ``path.<i>`` per layer."""
    tensors = {}
    for (path, _, stacked), host in zip(entries, fetched):
        if stacked:
            try:
                n = len(next(iter(host.values())))
            except (StopIteration, TypeError):
                continue
            for i in range(n):
                tensors[f"{path}.{i}"] = {
                    k: (int(v[i]) if k in ("nan", "inf") else float(v[i]))
                    for k, v in host.items()}
        else:
            tensors[path] = {
                k: (int(v) if k in ("nan", "inf") else float(v))
                for k, v in host.items()}
    return tensors


def step_summary(step=None):
    """Materialize pending stats if ``step`` hits the stride; called
    from ``telemetry.step_end`` (and usable standalone).  Returns the
    summary dict attached to step records as ``record["numerics"]`` or
    None off-stride.  Off-stride steps drop their pending device stats
    without a host sync."""
    global _step_seq
    if not _enabled:
        return None
    if step is None:
        step = _step_seq
    _step_seq = int(step) + 1
    with _lock:
        entries = list(_pending)
        del _pending[:]
    if int(step) % _stride != 0 or not entries:
        return None
    try:
        fetched = _materialize(entries)
    except Exception:  # never raises into training
        return None
    tensors = _expand(entries, fetched)
    first_nan = None
    for path, st in tensors.items():  # insertion order == forward order
        if st["nan"] or st["inf"]:
            first_nan = {"path": path, "layer": layer_of(path),
                         "nan": st["nan"], "inf": st["inf"]}
            break
    grad_sq = [st["l2"] ** 2 for p, st in tensors.items()
               if p.startswith("grad.")]
    summary = {
        "stride": _stride,
        "tensors": tensors,
        "first_nan": first_nan,
        "grad_norm": (sum(grad_sq) ** 0.5) if grad_sq else None,
    }
    _mirror_profiler(step, tensors)
    return summary


def _mirror_profiler(step, tensors):
    """Mirror per-path stats into live Perfetto counter tracks when a
    profiler session is running (module probed, never imported)."""
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is None or getattr(prof, "_state", None) != "run":
        return
    try:
        for path, st in tensors.items():
            prof.record_counter_event(
                "numerics/" + path,
                {"l2": st["l2"], "overflow": st["nan"] + st["inf"]})
    except Exception:
        pass


def consume(prefix):
    """Pop pending entries whose path starts with ``prefix`` and return
    them materialized as ``{path: stats}`` (host floats).  Used by
    ``Monitor.toc`` to drain its own taps without waiting for the
    stride."""
    with _lock:
        mine = [e for e in _pending if e[0].startswith(prefix)]
        _pending[:] = [e for e in _pending if not e[0].startswith(prefix)]
    if not mine:
        return {}
    try:
        fetched = _materialize(mine)
    except Exception:
        return {}
    return _expand(mine, fetched)


# --- divergence capture / replay --------------------------------------------

def arm_capture(out_dir):
    """Arm the capture hook: the next :func:`capture_step` with no
    explicit dir writes under ``out_dir``.  One-shot — capturing
    disarms, so a wedged run can't flood the disk."""
    global _capture_dir, _capture_armed
    _capture_dir = str(out_dir)
    _capture_armed = True


def capture_armed():
    return _capture_armed


def capture_step(net, inputs, rng_key=None, step=0, out_dir=None,
                 reason="flagged", builder=None, builder_kwargs=None):
    """Snapshot a flagged step for eager replay: inputs as ``.npz``,
    params/rng through the **async checkpointer** (training continues
    while the device→host copy drains), and a ``capture.json`` sidecar
    naming the ``builder`` (``"module:function"``) that can rebuild the
    net for ``tools/numerics_report.py --replay``.

    Returns the capture directory, or None when nothing is armed and no
    ``out_dir`` was given.  Never raises into training."""
    global _capture_armed
    out_dir = out_dir or (_capture_dir if _capture_armed else None)
    if out_dir is None:
        return None
    try:
        import numpy as np

        from .. import checkpoint as _ckpt

        step = int(step)
        cdir = os.path.join(str(out_dir), f"capture-{step}")
        os.makedirs(cdir, exist_ok=True)
        arrs = {}
        for i, a in enumerate(inputs):
            raw = getattr(a, "_data", a)
            arrs[f"input{i}"] = np.asarray(raw)
        np.savez(os.path.join(cdir, "inputs.npz"), **arrs)
        meta = {
            "record": "numerics_capture",
            "step": step,
            "reason": str(reason),
            "builder": builder,
            "builder_kwargs": builder_kwargs or {},
            "inputs": sorted(arrs, key=lambda k: int(k[5:])),
            "rng_key": ([int(v) for v in np.asarray(rng_key).ravel()]
                        if rng_key is not None else None),
            "time": time.time(),
        }
        # params ride the async checkpointer into the capture dir; the
        # manifest's extra block marks it as forensics, not a resume point
        _ckpt.save_checkpoint_async(
            cdir, step, net,
            extra={"numerics_capture": {"reason": str(reason)}})
        with open(os.path.join(cdir, "capture.json"), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
        _capture_armed = False
        return cdir
    except Exception:  # never raises into training
        return None


def load_capture(cdir):
    """Read a capture dir back: ``(meta, inputs)`` with inputs as host
    numpy arrays in their original positional order."""
    import numpy as np

    with open(os.path.join(cdir, "capture.json")) as f:
        meta = json.load(f)
    with np.load(os.path.join(cdir, "inputs.npz")) as z:
        inputs = [np.asarray(z[k]) for k in meta["inputs"]]
    return meta, inputs


class BisectResult:
    """Outcome of a :func:`bisect` replay.  ``ops`` is the per-op
    journal in dispatch order; ``first`` names the first op whose inputs
    were clean but whose outputs went nan/inf — the poisoned op."""

    def __init__(self):
        self.ops = []
        self.first = None


@contextmanager
def bisect():
    """Install a per-op NaN bisection hook on the op registry for an
    eager replay.  Every ``apply_op`` dispatch is journaled with
    inputs-clean/outputs-clean verdicts; the first clean→poisoned
    transition is recorded as ``result.first``.

    Forensics only: each op check is a host sync.  Never use in a
    training loop — this is the eager half of the tier, for
    ``numerics_report --replay``."""
    import numpy as np

    from ..ops import registry as _registry

    res = BisectResult()

    def _bad(a):
        if _is_tracer(a):
            return False
        try:
            arr = np.asarray(a)
        except Exception:
            return False
        if arr.dtype.kind not in "fc":
            return False
        return bool(np.isnan(arr).any() or np.isinf(arr).any())

    def hook(name, raws, outs):
        try:
            in_bad = any(_bad(r) for r in raws)
            out_bad = any(_bad(o) for o in outs)
            res.ops.append({"op": name or "<anonymous>",
                            "inputs_bad": in_bad, "outputs_bad": out_bad})
            if res.first is None and out_bad and not in_bad:
                res.first = {"op": name or "<anonymous>",
                             "index": len(res.ops) - 1}
        except Exception:
            pass

    prev = _registry._bisect_hook
    _registry._bisect_hook = hook
    try:
        yield res
    finally:
        _registry._bisect_hook = prev


if os.environ.get("MXNET_NUMERICS", "0") == "1":
    enable()
