"""Prometheus text-exposition renderer for telemetry snapshots.

Hoisted out of ``serving/metrics.py`` (r13) so a TRAINING job can expose
the same ``/metrics`` scrape the serving endpoint has: the renderer
reads only the telemetry module's host-side snapshots (counters, gauges,
``_Reservoir`` histograms), so it is owner-agnostic — serving keeps its
``MetricsServer`` handlers, ``telemetry.fleet.MetricsEndpoint`` reuses
the same text for training ranks.

Conventions (unchanged from r12):

* dotted telemetry names sanitize to ``mxt_*`` families
  (``serving.completed`` → ``mxt_serving_completed_total``);
* a name of the form ``base|key=value,...`` carries Prometheus labels
  (``serving.ttft_ms|replica=1`` renders as one labelled family);
* counters get ``_total``; histograms render as summaries
  (``quantile="0.5"/"0.9"/"0.99"`` over the rolling window plus
  ``_sum``/``_count`` over the all-time stream).

Schema details in docs/observability.md.
"""
from __future__ import annotations

import re
import sys

__all__ = ["prometheus_text"]


def _telemetry():
    # the parent package imports this module at its own import time;
    # resolve it lazily through sys.modules to keep the cycle harmless
    return sys.modules.get("mxnet_tpu.telemetry")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: rolling-histogram percentiles exposed as summary quantiles
_QUANTILES = ((50, "0.5"), (90, "0.9"), (99, "0.99"))


def _prom_name(name, prefix="mxt_"):
    """Dotted telemetry name → Prometheus metric family name."""
    body = _NAME_RE.sub("_", name)
    if body and body[0].isdigit():
        body = "_" + body
    return prefix + body


def _split_labels(name):
    """``"serving.ttft_ms|replica=0,lane=decode"`` →
    ``("serving.ttft_ms", {"replica": "0", "lane": "decode"})``."""
    if "|" not in name:
        return name, {}
    base, _, rest = name.partition("|")
    labels = {}
    for part in rest.split(","):
        k, _, v = part.partition("=")
        if k:
            labels[k.strip()] = v.strip()
    return base, labels


def _fmt_labels(labels, extra=None):
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(items.items()))
    return "{" + inner + "}"


def _fmt_value(v):
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(extra_gauges=None):
    """Render the telemetry module's current counters, gauges and
    histogram summaries (plus ``extra_gauges``, a dotted-name → value
    dict the caller wants on the same scrape) as Prometheus text."""
    tel = _telemetry()
    families = {}   # prom name -> {"type": ..., "samples": [(suffix, labels, value)]}

    def fam(name, mtype):
        f = families.get(name)
        if f is None:
            f = families[name] = {"type": mtype, "samples": []}
        return f

    for name, value in sorted(tel.counters().items()):
        base, labels = _split_labels(name)
        fam(_prom_name(base) + "_total", "counter")["samples"].append(
            ("", labels, value))
    gauges = dict(tel.gauges())
    if extra_gauges:
        gauges.update(extra_gauges)
    for name, value in sorted(gauges.items()):
        base, labels = _split_labels(name)
        fam(_prom_name(base), "gauge")["samples"].append(("", labels, value))
    for name, summ in sorted(tel.hists().items()):
        if summ is None:
            continue
        base, labels = _split_labels(name)
        f = fam(_prom_name(base), "summary")
        for p, q in _QUANTILES:
            val = summ.get(f"p{p}")
            if val is not None:
                f["samples"].append(("", dict(labels, quantile=q), val))
        f["samples"].append(("_sum", labels,
                             summ["mean"] * summ["count"]))
        f["samples"].append(("_count", labels, summ["count"]))
    lines = []
    for name in sorted(families):
        f = families[name]
        lines.append(f"# TYPE {name} {f['type']}")
        for suffix, labels, value in f["samples"]:
            lines.append(f"{name}{suffix}{_fmt_labels(labels)} "
                         f"{_fmt_value(value)}")
    return "\n".join(lines) + "\n"
