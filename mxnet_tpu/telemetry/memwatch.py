"""Device-memory observability: the live-buffer ledger + OOM post-mortem.

On TPUs the question that kills runs is "why did I OOM" — and the
runtime is the only layer that can answer it, because only the runtime
sees every raw-buffer bind.  This module keeps a **live-buffer ledger**:
every NDArray raw-buffer bind (creation, op results, materialized bulk
segments, rebinds through ``NDArray._data``) registers the buffer here,
and release is automatic — a ``weakref`` callback on the raw
``jax.Array`` fires when the buffer's python handle is collected, which
on an immutable-functional runtime IS the device-memory ground truth.
Donation consumption (the trainer/step-fusion/optimizer
``donate_argnums`` dispatch paths) releases buffers *early*, because the
device frees them at dispatch even while stale python aliases linger.

Accounting is shape×itemsize arithmetic only — tracking a buffer never
syncs, never touches device data (the same contract as
``telemetry.nbytes_of``).  Ledger state:

* ``live_bytes()`` / ``live_bytes_by_device()`` — current gauge;
* ``peak_live_bytes()`` — high-water mark since the last
  ``step_mark()`` (telemetry's ``step_begin`` resets it), the per-step
  watermark in the JSONL record;
* while the profiler runs, every ledger update mirrors a chrome-trace
  counter sample (``"ph": "C"``) so Perfetto renders a live-memory
  track alongside the span timeline.

The **OOM post-mortem** half: dispatch/sync sites call
:func:`annotate_oom` from their except paths (behind the one-boolean
``_enabled`` flag).  If the exception smells like an XLA allocation
failure (``RESOURCE_EXHAUSTED`` & friends), a ranked report of live
buffers (size, dtype, owning parameter/block name path, age in steps)
plus the top compiled artifacts by temp bytes (from
``telemetry.costs``) is written to disk and an :class:`OOMError` naming
the report file is raised in place of XLA's generic error.

Cost discipline: identical to ``telemetry``/``sanitizer`` — every hook
in the runtime is ``if _mw._enabled: ...``, one module-global boolean
test when off; no lock, no allocation.  ``telemetry.enable()`` /
``MXNET_TELEMETRY=1`` turns the ledger on with the rest of telemetry.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import weakref

from ..base import MXNetError

__all__ = ["enable", "disable", "is_enabled", "track", "donated", "adopt",
           "live_bytes", "live_bytes_by_device", "peak_live_bytes",
           "peak_live_bytes_by_device",
           "step_mark", "ledger_size", "snapshot", "write_postmortem",
           "annotate_oom", "looks_like_oom", "OOMError"]

#: THE fast-path flag: every runtime hook is ``if _mw._enabled: ...``
_enabled = False
_lock = threading.Lock()
_ledger = {}            # id(raw) -> _Entry
_live_total = 0
_live_by_device = {}    # device label -> bytes
_peak_total = 0
_peak_by_device = {}
_step_idx = 0           # pushed by telemetry.step_begin via step_mark()
_named = []             # [(weakref(NDArray holder), name)] — owner labels
_report_path = None

# concrete-array / tracer classes, resolved once at first enable() so the
# disabled path never imports jax
_ARRAY_CLS = None
_TRACER_CLS = None


class OOMError(MXNetError):
    """An XLA allocation failure, re-raised with the post-mortem path."""


class _Entry:
    __slots__ = ("nbytes", "shape", "dtype", "device", "per_device",
                 "owner", "birth_step", "ref")


def _ensure_classes():
    global _ARRAY_CLS, _TRACER_CLS
    if _ARRAY_CLS is None:
        import jax
        import jax.core

        _ARRAY_CLS = jax.Array
        _TRACER_CLS = jax.core.Tracer


def _nbytes(raw):
    size = 1
    for s in raw.shape:
        size *= int(s)
    import numpy as np

    return size * np.dtype(raw.dtype).itemsize


def _device_label(raw):
    try:
        dev = raw.device  # Device for single-device arrays, else Sharding
    except Exception:
        return "unknown"
    plat = getattr(dev, "platform", None)
    if plat is not None:
        return f"{plat}:{getattr(dev, 'id', 0)}"
    try:  # Sharding: label by the participating device set
        devs = sorted(dev.device_set, key=lambda d: d.id)
        return f"{devs[0].platform}:{','.join(str(d.id) for d in devs)}"
    except Exception:
        return "unknown"


def _per_device_bytes(raw, nbytes):
    """{device label: PHYSICAL bytes} for one array.  A replicated
    array occupies its full size on every device; a sharded array one
    shard per device (``sharding.shard_shape``).  This is the per-device
    truth the HBM-fit question needs — summing the map over devices
    exceeds the array's logical ``nbytes`` whenever anything is
    replicated, by design."""
    try:
        sharding = raw.sharding
        devs = sorted(sharding.device_set, key=lambda d: d.id)
    except Exception:
        return {_device_label(raw): nbytes}
    if len(devs) <= 1:
        return {_device_label(raw): nbytes}
    try:
        shard_shape = sharding.shard_shape(tuple(raw.shape))
        per = 1
        for s in shard_shape:
            per *= int(s)
        import numpy as np

        per *= np.dtype(raw.dtype).itemsize
    except Exception:
        per = nbytes // len(devs)
    return {f"{d.platform}:{d.id}": per for d in devs}


def _scope_owner():
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is None:
        return None
    prefix = prof.current_scope_prefix()
    return prefix.rstrip(":") if prefix else None


def _mirror_counter(total, by_device):
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is not None and prof.is_running():
        series = {"total": total}
        series.update(by_device)
        prof.record_counter_event("memwatch.live_bytes", series)


def _add_locked(e):
    global _live_total, _peak_total
    _live_total += e.nbytes
    for dev, b in e.per_device.items():
        cur = _live_by_device.get(dev, 0) + b
        _live_by_device[dev] = cur
        if cur > _peak_by_device.get(dev, 0):
            _peak_by_device[dev] = cur
    if _live_total > _peak_total:
        _peak_total = _live_total


def _sub_locked(e):
    global _live_total
    _live_total -= e.nbytes
    for dev, b in e.per_device.items():
        cur = _live_by_device.get(dev, 0) - b
        if cur > 0:
            _live_by_device[dev] = cur
        else:
            _live_by_device.pop(dev, None)


# -- the ledger ---------------------------------------------------------------

def track(raw, owner=None):
    """Register a raw device buffer in the ledger (idempotent per
    buffer: shared handles — ``detach()``/``_alias()`` — count once).
    Placeholders (pending bulk segments), tracers and non-arrays are
    ignored; accounting is shape×itemsize, never a sync.  Release is
    automatic via a weakref callback when the buffer is collected."""
    if not _enabled:
        return
    if not isinstance(raw, _ARRAY_CLS) or isinstance(raw, _TRACER_CLS):
        return
    key = id(raw)
    with _lock:
        e = _ledger.get(key)
        if e is not None:
            if e.ref() is raw:
                if owner is not None and e.owner is None:
                    e.owner = owner
                return
            # id reuse: the registered buffer died without its callback
            # having run yet — evict the stale entry first
            del _ledger[key]
            _sub_locked(e)
        e = _Entry()
        try:
            e.nbytes = _nbytes(raw)
            e.shape = tuple(int(s) for s in raw.shape)
            e.dtype = str(raw.dtype)
            e.device = _device_label(raw)
            e.per_device = _per_device_bytes(raw, e.nbytes)
        except Exception:
            return
        e.owner = owner if owner is not None else _scope_owner()
        e.birth_step = _step_idx

        def _cb(ref, _key=key):
            with _lock:
                dead = _ledger.get(_key)
                if dead is not None and dead.ref is ref:
                    del _ledger[_key]
                    _sub_locked(dead)
                total, by_dev = _live_total, dict(_live_by_device)
            _mirror_counter(total, by_dev)

        e.ref = weakref.ref(raw, _cb)
        _ledger[key] = e
        _add_locked(e)
        total, by_dev = _live_total, dict(_live_by_device)
    _mirror_counter(total, by_dev)


def donated(raws):
    """Donation consumption: the dispatch that just ran handed these
    buffers to a ``donate_argnums`` jitted call, so the device frees
    them NOW even though python aliases may linger.  Releases them from
    the ledger early; the eventual GC callback finds nothing (entry
    identity is checked, so a reused id never double-releases)."""
    if not _enabled:
        return
    with _lock:
        changed = False
        for raw in raws:
            e = _ledger.get(id(raw))
            if e is None or e.ref() is not raw:
                continue
            del _ledger[id(raw)]
            _sub_locked(e)
            changed = True
        total, by_dev = _live_total, dict(_live_by_device)
    if changed:
        _mirror_counter(total, by_dev)


def adopt(holder, name):
    """Label an NDArray *holder* (not a buffer) with a stable owner name
    — parameters register their data/grad handles so the post-mortem can
    name buffers by parameter path across rebinds (optimizer updates
    rebind ``_raw``; the holder identity survives)."""
    if not _enabled:
        return
    try:
        ref = weakref.ref(holder)
    except TypeError:
        return
    with _lock:
        _named.append((ref, name))


def step_mark(step_idx):
    """Reset the per-step peak watermark to the current live level
    (called from ``telemetry.step_begin``)."""
    global _peak_total, _step_idx
    if not _enabled:
        return
    with _lock:
        _step_idx = step_idx
        _peak_total = _live_total
        _peak_by_device.clear()
        _peak_by_device.update(_live_by_device)


def live_bytes():
    """Current tracked device bytes (sum over devices)."""
    with _lock:
        return _live_total


def live_bytes_by_device():
    with _lock:
        return dict(_live_by_device)


def peak_live_bytes():
    """High-water mark of ``live_bytes`` since the last step_mark()."""
    with _lock:
        return _peak_total


def peak_live_bytes_by_device():
    """Per-device high-water marks since the last step_mark() — the
    number that decides HBM fit under a sharded layout (the sum hides
    replication; the per-device peak does not)."""
    with _lock:
        return dict(_peak_by_device)


def ledger_size():
    with _lock:
        return len(_ledger)


def snapshot():
    """Ranked (largest first) list of live-buffer dicts — the post-mortem
    body, also useful interactively.  Owner names resolve through the
    registered holders at snapshot time, so a parameter rebound since
    bind still reports its parameter path."""
    with _lock:
        owners = _resolve_owners_locked()
        rows = []
        for key, e in _ledger.items():
            rows.append({
                "nbytes": e.nbytes,
                "shape": list(e.shape),
                "dtype": e.dtype,
                "device": e.device,
                "owner": owners.get(key, e.owner),
                "age_steps": max(0, _step_idx - e.birth_step),
            })
    rows.sort(key=lambda r: -r["nbytes"])
    return rows


def _resolve_owners_locked():
    owners = {}
    alive = []
    for ref, name in _named:
        holder = ref()
        if holder is None:
            continue
        alive.append((ref, name))
        try:
            owners[id(holder._raw)] = name
        except Exception:
            pass
    _named[:] = alive
    return owners


# -- OOM post-mortem ----------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory",
                "Failed to allocate", "failed to allocate",
                "Allocation failure", "OOM")


def looks_like_oom(exc):
    """Does this exception look like an XLA/device allocation failure?"""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKERS)


def write_postmortem(path=None, context="", error=""):
    """Dump the ranked live-buffer report + the top compiled artifacts
    by temp bytes to ``path`` (default: ``MXNET_MEMWATCH_REPORT`` or
    ``memwatch_oom_<pid>.json`` in the cwd).  Returns the path."""
    from . import costs as _costs

    if path is None:
        path = _report_path or os.environ.get(
            "MXNET_MEMWATCH_REPORT") or f"memwatch_oom_{os.getpid()}.json"
    buffers = snapshot()
    with _lock:
        report = {
            "context": context,
            "error": error,
            "wall_time": time.time(),
            "step": _step_idx,
            "live_bytes": _live_total,
            "peak_live_bytes": _peak_total,
            "live_bytes_by_device": dict(_live_by_device),
            "n_live_buffers": len(_ledger),
        }
    report["buffers"] = buffers
    report["top_artifacts_by_temp_bytes"] = \
        _costs.top_artifacts(n=10, by="temp_bytes")
    # prescription: when the memory planner is loaded, re-plan the last
    # model under the escalation ladder (higher remat tier, host
    # offload, smaller batch) and name the cheapest fix
    mem = sys.modules.get("mxnet_tpu.memory")
    if mem is not None:
        try:
            rx = mem.prescribe()
            if rx is not None:
                report["prescription"] = rx
        except Exception:
            pass  # reporting never masks the original failure
    # the flight recorder joins the post-mortem: the last few completed
    # request traces show WHAT the server was doing when memory blew
    tr = sys.modules.get("mxnet_tpu.telemetry.tracing")
    if tr is not None and tr.is_enabled():
        try:
            report["recent_traces"] = tr.recent(8)
        except Exception:
            pass
    # ...and the training flight recorder: the last step records +
    # anomaly events before the OOM, rank-stamped (telemetry.fleet)
    fl = sys.modules.get("mxnet_tpu.telemetry.fleet")
    if fl is not None and fl.is_enabled():
        try:
            report["recent_steps"] = fl.recent(16)
        except Exception:
            pass
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)
    return path


def annotate_oom(exc, context=""):
    """Called from dispatch/sync except paths (behind the ``_enabled``
    flag): if ``exc`` is an allocation failure, write the post-mortem
    and raise :class:`OOMError` naming the report file; otherwise
    return so the caller re-raises the original."""
    if not _enabled or not looks_like_oom(exc):
        return
    try:
        path = write_postmortem(context=context, error=str(exc))
    except Exception:
        return  # never let reporting mask the original failure
    fix = ""
    mem = sys.modules.get("mxnet_tpu.memory")
    if mem is not None:
        try:
            rx = mem.planner.last_prescription()
            rec = rx and rx.get("recommendation")
            if rec:
                fix = (f"\ncheapest fix that fits: {rec['change']} "
                       f"(predicted peak {rec['predicted_peak_gib']} GiB)")
        except Exception:
            pass
    raise OOMError(
        f"device allocation failure during {context or 'dispatch'}: {exc}\n"
        f"memwatch post-mortem (ranked live buffers + top compiled "
        f"artifacts by temp bytes) written to {path}{fix}") from exc


# -- lifecycle ----------------------------------------------------------------

def enable(report_path=None):
    """Turn the ledger on (clears prior state).  Buffers bound while
    disabled are not tracked retroactively — enable before building the
    model for an exact ledger."""
    global _enabled, _report_path
    _ensure_classes()
    with _lock:
        _clear_locked()
        _report_path = report_path
    _enabled = True


def disable():
    global _enabled
    _enabled = False
    with _lock:
        _clear_locked()


def is_enabled():
    return _enabled


def _clear_locked():
    global _live_total, _peak_total, _step_idx
    _ledger.clear()
    _live_by_device.clear()
    _peak_by_device.clear()
    _named.clear()
    _live_total = 0
    _peak_total = 0
    _step_idx = 0
