"""Mixture-of-Experts layer + expert parallelism (EP).

Reference: NONE — MoE is ABSENT in the reference (SURVEY §2.3 D9); this is
new TPU-native capability, built so a stock ``gluon.Trainer`` trains it and
``shard_moe`` adds expert parallelism over an ``ep`` mesh axis.

TPU-first design decisions:
- Expert weights are STACKED into single (E, ...) parameters, so the whole
  expert bank is one batched einsum on the MXU — not E small matmuls.  With
  ``shard_moe`` the expert axis is sharded over ``ep`` and GSPMD derives the
  token all-to-all (dispatch einsum) / all-reduce (combine einsum), the same
  way psum is derived for dp.
- Routing is FIXED-CAPACITY (dispatch/combine tensors of static shape
  (N, E, C)); overflow tokens are dropped from the expert path (standard
  Switch/GShard semantics) and pass through the residual stream.  Dynamic
  per-expert token counts would not compile for the MXU.
- Two routers:
  * ``topk`` — tokens pick experts (GShard/Mixtral style, k experts per
    token, gates renormalised over the chosen k); needs the load-balancing
    auxiliary loss to avoid collapse (see ``collect_aux``).
  * ``expert_choice`` — experts pick tokens (top-C over the token axis);
    perfectly load-balanced by construction, no aux loss needed.  CAVEAT
    for causal decoders: expert assignment of token t depends on the
    top-C competition against LATER tokens, so training sees (weak)
    future information that autoregressive inference won't have — the
    known expert-choice-in-decoder train/inference mismatch.  Prefer
    ``topk`` for production causal-LM training; expert_choice is ideal
    for encoders and fine for routing-plumbing tests/dryruns.
- Router math runs in float32 regardless of activation dtype (bf16 routing
  logits are a known training-instability source).
"""
from __future__ import annotations

import math

from ..base import MXNetError
from ..gluon.block import HybridBlock

__all__ = ["MoEMLP", "collect_aux", "shard_moe"]


# --- aux-loss collection ----------------------------------------------------

# thread-local, matching autograd._AGState / parallel._STATE: concurrent
# per-thread training must not share a sink
import threading as _threading

_TLS = _threading.local()


def _sink():
    return getattr(_TLS, "aux_sink", None)


class collect_aux:
    """Collect per-layer load-balancing losses during an EAGER forward::

        with moe.collect_aux() as aux:
            logits = net(x)                       # not hybridized
            loss = ce(logits, y) + 0.01 * sum(aux)

    Each entry is a tape-connected scalar NDArray (an extra output of the
    MoE op), so ``backward()`` trains the router through it.  Under
    ``hybridize()`` tracing this raises: traced values can't escape the
    compiled graph — train un-hybridized when using the topk router with
    aux loss, or use router="expert_choice" (needs no aux loss).
    """

    def __enter__(self):
        self._prev = _sink()
        _TLS.aux_sink = []
        return _TLS.aux_sink

    def __exit__(self, *exc):
        _TLS.aux_sink = self._prev
        return False


class MoEMLP(HybridBlock):
    """Sparse SwiGLU feed-forward: each token is processed by k of E
    experts, outputs combined with the (renormalised) router gates.

    Drop-in replacement for a dense SwiGLU MLP of the same
    hidden/intermediate sizes (e.g. ``models.llama.LlamaMLP``).
    """

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 num_experts_per_tok=2, capacity_factor=1.25,
                 router="topk", **kwargs):
        super().__init__(**kwargs)
        if router not in ("topk", "expert_choice"):
            raise MXNetError(f"unknown MoE router {router!r}")
        if num_experts_per_tok > num_experts:
            raise MXNetError("num_experts_per_tok must be <= num_experts")
        self._h = hidden_size
        self._i = intermediate_size
        self._e = num_experts
        self._k = num_experts_per_tok
        self._cf = capacity_factor
        self._router = router
        with self.name_scope():
            self.router_weight = self.params.get(
                "router_weight", shape=(num_experts, hidden_size))
            self.gate_weight = self.params.get(
                "gate_weight",
                shape=(num_experts, intermediate_size, hidden_size))
            self.up_weight = self.params.get(
                "up_weight",
                shape=(num_experts, intermediate_size, hidden_size))
            self.down_weight = self.params.get(
                "down_weight",
                shape=(num_experts, hidden_size, intermediate_size))

    def _capacity(self, n):
        return max(1, int(math.ceil(n * self._k * self._cf / self._e)))

    def hybrid_forward(self, F, x, router_weight, gate_weight, up_weight,
                       down_weight):
        from ..ops.registry import apply_op

        e, k, router = self._e, self._k, self._router
        cap_of = self._capacity

        def _f(xr, rw, gw, uw, dw):
            import jax
            import jax.numpy as jnp
            from jax import lax

            b, t, h = xr.shape
            n = b * t
            c = min(cap_of(n), n)  # an expert can't hold > n tokens
            xt = xr.reshape(n, h)
            logits = xt.astype(jnp.float32) @ rw.astype(jnp.float32).T
            probs = jax.nn.softmax(logits, axis=-1)          # (N, E) f32

            if router == "expert_choice":
                # experts pick tokens: balanced by construction
                gates, idx = lax.top_k(probs.T, c)           # (E, C)
                disp = jax.nn.one_hot(idx, n, dtype=xr.dtype)  # (E, C, N)
                ein = jnp.einsum("ecn,nh->ech", disp, xt)
                out_e = _expert_ffn(ein, gw, uw, dw)
                y = jnp.einsum("ecn,ec,ech->nh", disp,
                               gates.astype(xr.dtype), out_e)
                aux = jnp.zeros((), jnp.float32)
            else:
                gates, idx = lax.top_k(probs, k)             # (N, k)
                gates = gates / gates.sum(-1, keepdims=True)
                disp = jnp.zeros((n, e, c), xr.dtype)
                comb = jnp.zeros((n, e, c), xr.dtype)
                counts = jnp.zeros((e,), jnp.int32)
                rows = jnp.arange(n)
                for s in range(k):  # k is tiny; unrolled at trace time
                    sel = idx[:, s]                           # (N,)
                    onehot = jax.nn.one_hot(sel, e, dtype=jnp.int32)
                    pos = (onehot * (jnp.cumsum(onehot, axis=0) - 1
                                     + counts[None, :])).sum(-1)
                    keep = (pos < c).astype(xr.dtype)
                    slot = jnp.clip(pos, 0, c - 1)
                    disp = disp.at[rows, sel, slot].add(keep)
                    comb = comb.at[rows, sel, slot].add(
                        keep * gates[:, s].astype(xr.dtype))
                    counts = counts + onehot.sum(0)
                ein = jnp.einsum("nec,nh->ech", disp, xt)
                out_e = _expert_ffn(ein, gw, uw, dw)
                y = jnp.einsum("nec,ech->nh", comb, out_e)
                # Switch-style load-balance loss: E * sum_e f_e * P_e
                frac = jax.nn.one_hot(idx[:, 0], e,
                                      dtype=jnp.float32).mean(0)
                aux = e * (frac * probs.mean(0)).sum()
            return y.reshape(b, t, h), aux

        y, aux = apply_op(_f, x, router_weight, gate_weight, up_weight,
                          down_weight, name="moe_mlp")
        sink = _sink()
        if sink is not None:
            import jax

            if isinstance(aux._data, jax.core.Tracer):
                raise MXNetError(
                    "collect_aux() cannot cross a hybridize() trace; train "
                    "un-hybridized with the topk router, or use "
                    "router='expert_choice' (no aux loss needed)")
            sink.append(aux)
        return y


def _expert_ffn(ein, gw, uw, dw):
    """SwiGLU over the stacked expert bank: ein (E, C, H) → (E, C, H).
    One batched einsum per projection — the MXU sees E-batched matmuls."""
    import jax
    import jax.numpy as jnp

    g = jnp.einsum("ech,eih->eci", ein, gw.astype(ein.dtype))
    u = jnp.einsum("ech,eih->eci", ein, uw.astype(ein.dtype))
    act = g * jax.nn.sigmoid(g) * u
    return jnp.einsum("eci,ehi->ech", act, dw.astype(ein.dtype))


def moe_param_specs(block, ep_axis="ep", tp_axis=None):
    """{param: partition-spec tuple} for an MoE block — the rule table
    :func:`shard_moe` applies, reusable against abstract shapes (the 8B
    lowering proof).  Pass ``ep_axis``/``tp_axis`` as None when absent
    from the target mesh."""
    ep, tp = ep_axis, tp_axis
    return {
        block.router_weight: (None, None),
        block.gate_weight: (ep, tp, None),
        block.up_weight: (ep, tp, None),
        block.down_weight: (ep, None, tp),
    }


def shard_moe(block, mesh=None, ep_axis="ep", tp_axis=None):
    """Expert parallelism: shard the stacked expert bank over ``ep_axis``
    (optionally tensor-parallel within each expert over ``tp_axis``).
    Either axis may be absent from the mesh — a dp×tp mesh still gets the
    experts tp-sharded (the expert bank dominates MoE parameter memory).
    GSPMD derives the token all-to-all from the dispatch/combine einsums —
    the TPU-native analog of hand-written MoE a2a kernels."""
    from .. import parallel

    mesh = mesh or parallel.current_mesh()
    if mesh is None:
        return block
    ep = ep_axis if (ep_axis and ep_axis in mesh.shape) else None
    tp = tp_axis if (tp_axis and tp_axis in mesh.shape) else None
    if ep is None and tp is None:
        return block
    for p, spec in moe_param_specs(block, ep_axis=ep,
                                   tp_axis=tp).items():
        parallel.shard_param(p, spec, mesh)
    return block
