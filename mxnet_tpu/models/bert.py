"""BERT model family.

Reference: GluonNLP ``gluonnlp/model/bert.py:?`` (the BASELINE config 3
"BERT-base" workload) — BERTEncoder over the contrib interleaved attention
ops, token/segment/position embeddings, pooler, MLM + NSP heads.

TPU-native: fused ``dot_product_attention``, GELU via the op library,
everything a gluon HybridBlock so one ``hybridize()`` compiles the whole
step; bf16-friendly (LayerNorm stats in fp32).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn
from .transformer import MultiHeadAttention

__all__ = ["BERTEncoder", "BERTModel", "bert_base", "bert_large",
           "BERTClassifier", "BERT_CONFIGS"]

BERT_CONFIGS = {
    "bert_base": dict(num_layers=12, units=768, hidden_size=3072,
                      num_heads=12, max_length=512),
    "bert_large": dict(num_layers=24, units=1024, hidden_size=4096,
                       num_heads=16, max_length=512),
    "bert_tiny": dict(num_layers=2, units=128, hidden_size=512,
                      num_heads=2, max_length=128),
}


class BERTEncoderCell(HybridBlock):
    """Post-norm encoder layer with GELU FFN (BERT arrangement)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads, dropout)
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.layer_norm_att = nn.LayerNorm(in_channels=units)
            self.layer_norm_ffn = nn.LayerNorm(in_channels=units)
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None):
        att = self.attention(x, x, x, mask)
        x = self.layer_norm_att(x + att)
        h = F.leaky_relu(self.ffn_1(x), act_type="gelu")
        h = self.ffn_2(h)
        if self._dropout:
            h = F.dropout(h, p=self._dropout)
        return self.layer_norm_ffn(x + h)


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, max_length=512, dropout=0.1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._max_length = max_length
        self._units = units
        with self.name_scope():
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units))
            self.layer_norm = nn.LayerNorm(in_channels=units)
            self.transformer_cells = nn.HybridSequential(prefix="")
            for _ in range(num_layers):
                self.transformer_cells.add(BERTEncoderCell(
                    units, hidden_size, num_heads, dropout))
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None, position_weight=None):
        t = x.shape[1]
        x = x + F.slice_axis(position_weight, axis=0, begin=0,
                             end=t).expand_dims(0)
        x = self.layer_norm(x)
        if self._dropout:
            x = F.dropout(x, p=self._dropout)
        for cell in self.transformer_cells:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """Token+segment embeddings → encoder → (sequence output, pooled,
    [MLM logits, NSP logits]) (reference: gluonnlp BERTModel)."""

    def __init__(self, vocab_size=30522, token_type_vocab_size=2,
                 num_layers=12, units=768, hidden_size=3072, num_heads=12,
                 max_length=512, dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units)
            self.token_type_embed = nn.Embedding(token_type_vocab_size,
                                                 units)
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, max_length, dropout)
            if use_pooler:
                self.pooler = nn.Dense(units, activation="tanh",
                                       flatten=False, prefix="pooler_")
            if use_decoder:
                self.decoder = nn.HybridSequential(prefix="decoder_")
                with self.decoder.name_scope():
                    self.decoder.add(nn.Dense(units, flatten=False))
                    self.decoder.add(nn.GELU())
                    self.decoder.add(nn.LayerNorm(in_channels=units))
                    self.decoder.add(nn.Dense(vocab_size, flatten=False))
            if use_classifier:
                self.classifier = nn.Dense(2, flatten=False,
                                           prefix="nsp_")

    def _make_mask(self, F, valid_length, t, batch):
        from ..ndarray import NDArray
        import jax.numpy as jnp

        if valid_length is None:
            return None
        # (B,) lengths → (B, 1, 1, T) boolean attend-mask
        ar = F.arange(0, t).reshape((1, 1, 1, t))
        vl = valid_length.reshape((-1, 1, 1, 1))
        return F.broadcast_lesser(ar, vl)

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        mask = self._make_mask(F, valid_length, inputs.shape[1],
                               inputs.shape[0])
        seq = self.encoder(x, mask)
        outputs = [seq]
        if self._use_pooler:
            pooled = self.pooler(F.slice_axis(seq, axis=1, begin=0,
                                              end=1).squeeze(axis=1))
            outputs.append(pooled)
            if self._use_classifier:
                outputs.append(self.classifier(pooled))
        if self._use_decoder:
            outputs.append(self.decoder(seq))
        return outputs[0] if len(outputs) == 1 else tuple(outputs)


class BERTClassifier(HybridBlock):
    """Fine-tuning head (reference: gluonnlp BERTClassifier)."""

    def __init__(self, bert, num_classes=2, dropout=0.0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self.bert = bert
        with self.name_scope():
            self.classifier = nn.HybridSequential(prefix="cls_")
            if dropout:
                self.classifier.add(nn.Dropout(rate=dropout))
            self.classifier.add(nn.Dense(num_classes, flatten=False))

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        outs = self.bert(inputs, token_types, valid_length)
        pooled = outs[1]
        return self.classifier(pooled)


def _make(config, **kwargs):
    cfg = dict(BERT_CONFIGS[config])
    cfg.update(kwargs)
    return BERTModel(**cfg)


def bert_base(**kwargs):
    return _make("bert_base", **kwargs)


def bert_large(**kwargs):
    return _make("bert_large", **kwargs)


def bert_tiny(**kwargs):
    return _make("bert_tiny", **kwargs)
