"""BERT model family.

Reference: GluonNLP ``gluonnlp/model/bert.py:?`` (the BASELINE config 3
"BERT-base" workload) — BERTEncoder over the contrib interleaved attention
ops, token/segment/position embeddings, pooler, MLM + NSP heads.

TPU-native: fused ``dot_product_attention``, GELU via the op library,
everything a gluon HybridBlock so one ``hybridize()`` compiles the whole
step; bf16-friendly (LayerNorm stats in fp32).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn
from .transformer import MultiHeadAttention

__all__ = ["BERTEncoder", "BERTModel", "bert_base", "bert_large",
           "BERTClassifier", "BERT_CONFIGS", "bert_to_symbol",
           "export_bert_onnx"]

BERT_CONFIGS = {
    "bert_base": dict(num_layers=12, units=768, hidden_size=3072,
                      num_heads=12, max_length=512),
    "bert_large": dict(num_layers=24, units=1024, hidden_size=4096,
                       num_heads=16, max_length=512),
    "bert_tiny": dict(num_layers=2, units=128, hidden_size=512,
                      num_heads=2, max_length=128),
}


class BERTEncoderCell(HybridBlock):
    """Post-norm encoder layer with GELU FFN (BERT arrangement)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads, dropout)
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.layer_norm_att = nn.LayerNorm(in_channels=units)
            self.layer_norm_ffn = nn.LayerNorm(in_channels=units)
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None):
        att = self.attention(x, x, x, mask)
        x = self.layer_norm_att(x + att)
        h = F.leaky_relu(self.ffn_1(x), act_type="gelu")
        h = self.ffn_2(h)
        if self._dropout:
            h = F.dropout(h, p=self._dropout)
        return self.layer_norm_ffn(x + h)


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, max_length=512, dropout=0.1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._max_length = max_length
        self._units = units
        with self.name_scope():
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units))
            self.layer_norm = nn.LayerNorm(in_channels=units)
            self.transformer_cells = nn.HybridSequential(prefix="")
            for _ in range(num_layers):
                self.transformer_cells.add(BERTEncoderCell(
                    units, hidden_size, num_heads, dropout))
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None, position_weight=None):
        t = x.shape[1]
        x = x + F.slice_axis(position_weight, axis=0, begin=0,
                             end=t).expand_dims(0)
        x = self.layer_norm(x)
        if self._dropout:
            x = F.dropout(x, p=self._dropout)
        for cell in self.transformer_cells:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """Token+segment embeddings → encoder → (sequence output, pooled,
    [MLM logits, NSP logits]) (reference: gluonnlp BERTModel)."""

    def __init__(self, vocab_size=30522, token_type_vocab_size=2,
                 num_layers=12, units=768, hidden_size=3072, num_heads=12,
                 max_length=512, dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units)
            self.token_type_embed = nn.Embedding(token_type_vocab_size,
                                                 units)
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, max_length, dropout)
            if use_pooler:
                self.pooler = nn.Dense(units, activation="tanh",
                                       flatten=False, prefix="pooler_")
            if use_decoder:
                self.decoder = nn.HybridSequential(prefix="decoder_")
                with self.decoder.name_scope():
                    self.decoder.add(nn.Dense(units, flatten=False))
                    self.decoder.add(nn.GELU())
                    self.decoder.add(nn.LayerNorm(in_channels=units))
                    self.decoder.add(nn.Dense(vocab_size, flatten=False))
            if use_classifier:
                self.classifier = nn.Dense(2, flatten=False,
                                           prefix="nsp_")

    def _make_mask(self, F, valid_length, t, batch):
        from ..ndarray import NDArray
        import jax.numpy as jnp

        if valid_length is None:
            return None
        # (B,) lengths → (B, 1, 1, T) boolean attend-mask
        ar = F.arange(0, t).reshape((1, 1, 1, t))
        vl = valid_length.reshape((-1, 1, 1, 1))
        return F.broadcast_lesser(ar, vl)

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        mask = self._make_mask(F, valid_length, inputs.shape[1],
                               inputs.shape[0])
        seq = self.encoder(x, mask)
        outputs = [seq]
        if self._use_pooler:
            pooled = self.pooler(F.slice_axis(seq, axis=1, begin=0,
                                              end=1).squeeze(axis=1))
            outputs.append(pooled)
            if self._use_classifier:
                outputs.append(self.classifier(pooled))
        if self._use_decoder:
            outputs.append(self.decoder(seq))
        return outputs[0] if len(outputs) == 1 else tuple(outputs)


class BERTClassifier(HybridBlock):
    """Fine-tuning head (reference: gluonnlp BERTClassifier)."""

    def __init__(self, bert, num_classes=2, dropout=0.0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self.bert = bert
        with self.name_scope():
            self.classifier = nn.HybridSequential(prefix="cls_")
            if dropout:
                self.classifier.add(nn.Dropout(rate=dropout))
            self.classifier.add(nn.Dense(num_classes, flatten=False))

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        outs = self.bert(inputs, token_types, valid_length)
        pooled = outs[1]
        return self.classifier(pooled)


def _make(config, **kwargs):
    cfg = dict(BERT_CONFIGS[config])
    cfg.update(kwargs)
    return BERTModel(**cfg)


def bert_base(**kwargs):
    return _make("bert_base", **kwargs)


def bert_large(**kwargs):
    return _make("bert_large", **kwargs)


def bert_tiny(**kwargs):
    return _make("bert_tiny", **kwargs)


def bert_to_symbol(net, batch, seq_len):
    """Rebuild a trained :class:`BERTModel`'s INFERENCE forward as an
    ``mxnet_tpu.symbol`` graph whose variable names are the net's own
    parameter names, so ``net.collect_params()`` binds it directly —
    the bridge from the gluon/StableHLO export world to the op-graph
    consumers (ONNX via :func:`export_bert_onnx`; reference:
    GluonNLP exported its BERT through the symbol API the same way).

    Matches ``BERTModel.hybrid_forward`` with ``token_types`` given and
    ``valid_length=None`` (full attention), dropout=identity
    (inference).  Returns ``(symbol_group, param_dict)`` where the
    group outputs are (sequence, pooled, nsp_logits, mlm_logits) — the
    heads present on ``net``.
    """
    from .. import symbol as S

    params = net.collect_params()
    pname = {}
    for name, p in params.items():
        pname[p] = name

    def var(p):
        return S.var(pname[p])

    enc = net.encoder
    units = enc._units
    cells = list(enc.transformer_cells)
    heads = cells[0].attention._num_heads
    d = units // heads

    ids = S.var("data0")
    seg = S.var("data1")
    x = S.Embedding(ids, var(net.word_embed.weight),
                    input_dim=net.word_embed._input_dim,
                    output_dim=units, name="word_embed")
    x = S.broadcast_add(
        x, S.Embedding(seg, var(net.token_type_embed.weight),
                       input_dim=net.token_type_embed._input_dim,
                       output_dim=units, name="seg_embed"),
        name="embed_sum")
    pos = S.slice_axis(var(enc.position_weight), axis=0, begin=0,
                       end=seq_len, name="pos_slice")
    x = S.broadcast_add(x, S.expand_dims(pos, axis=0), name="pos_add")
    x = S.LayerNorm(x, var(enc.layer_norm.gamma),
                    var(enc.layer_norm.beta), name="embed_ln")

    def dense(t, layer, tag):
        return S.FullyConnected(t, var(layer.weight), var(layer.bias),
                                num_hidden=layer.weight.shape[0],
                                flatten=False, name=tag)

    for i, cell in enumerate(cells):
        att = cell.attention

        def split(t, tag):
            t = S.Reshape(t, shape=(batch, seq_len, heads, d),
                          name=f"{tag}_split")
            return S.transpose(t, axes=(0, 2, 1, 3), name=f"{tag}_bhtd")

        q = split(dense(x, att.proj_query, f"l{i}_q"), f"l{i}_qh")
        k = split(dense(x, att.proj_key, f"l{i}_k"), f"l{i}_kh")
        v = split(dense(x, att.proj_value, f"l{i}_v"), f"l{i}_vh")
        kt = S.transpose(k, axes=(0, 1, 3, 2), name=f"l{i}_kT")
        scores = S.batch_dot(q, kt, name=f"l{i}_scores") / float(
            np.sqrt(d))
        prob = S.softmax(scores, axis=-1, name=f"l{i}_att")
        ctx = S.batch_dot(prob, v, name=f"l{i}_ctx")
        ctx = S.transpose(ctx, axes=(0, 2, 1, 3), name=f"l{i}_bthd")
        ctx = S.Reshape(ctx, shape=(batch, seq_len, units),
                        name=f"l{i}_merge")
        proj = dense(ctx, att.proj_out, f"l{i}_attout")
        x = S.LayerNorm(S.broadcast_add(x, proj, name=f"l{i}_res1"),
                        var(cell.layer_norm_att.gamma),
                        var(cell.layer_norm_att.beta), name=f"l{i}_ln1")
        h = S.LeakyReLU(dense(x, cell.ffn_1, f"l{i}_ffn1"),
                        act_type="gelu", name=f"l{i}_gelu")
        h = dense(h, cell.ffn_2, f"l{i}_ffn2")
        x = S.LayerNorm(S.broadcast_add(x, h, name=f"l{i}_res2"),
                        var(cell.layer_norm_ffn.gamma),
                        var(cell.layer_norm_ffn.beta), name=f"l{i}_ln2")

    outs = [x]
    if net._use_pooler:
        first = S.Reshape(S.slice_axis(x, axis=1, begin=0, end=1,
                                       name="cls_slice"),
                          shape=(batch, units), name="cls_tok")
        pooled = S.tanh(dense(first, net.pooler, "pooler_fc"),
                        name="pooled")
        outs.append(pooled)
        if net._use_classifier:
            outs.append(dense(pooled, net.classifier, "nsp"))
    if net._use_decoder:
        dec = list(net.decoder)
        hme = dense(x, dec[0], "mlm_fc")
        hme = S.LeakyReLU(hme, act_type="gelu", name="mlm_gelu")
        hme = S.LayerNorm(hme, var(dec[2].gamma), var(dec[2].beta),
                          name="mlm_ln")
        outs.append(dense(hme, dec[3], "mlm_logits"))

    pdict = {name: p.data() for name, p in params.items()}
    return S.Group(outs), pdict


def export_bert_onnx(net, path, batch, seq_len):
    """Export a trained BERTModel to ONNX (opset 13) via
    :func:`bert_to_symbol` + ``contrib.onnx.export_model`` — VERDICT r3
    weak 8 closed: the NLP zoo exports, not just CNN/MLP."""
    from ..contrib.onnx import export_model

    sym, params = bert_to_symbol(net, batch, seq_len)
    return export_model(
        sym, params, [(batch, seq_len), (batch, seq_len)],
        input_types=[np.int32, np.int32], onnx_file_path=path)
