"""Model families beyond the vision zoo (BASELINE configs 3 and 5).

``transformer``/``bert`` mirror GluonNLP's model surface; ``llama`` is the
stretch config (modern LLM under mx.tpu() — NEW capability vs the
reference).
"""
from . import transformer
from .transformer import Transformer
from . import bert
from .bert import BERTModel, BERTClassifier, bert_base, bert_large, \
    bert_tiny


def __getattr__(name):
    if name in ("llama", "fm", "moe"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
