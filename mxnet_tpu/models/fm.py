"""Factorization machine (BASELINE config 4: the PS-shaped sparse
workload).

Reference: ``example/sparse/factorization_machine/`` (+ linear
classification examples) — CSR minibatches from ``LibSVMIter``, row_sparse
weight/embedding gradients pushed through the parameter-server kvstore,
server-side lazy updates touching only live rows (SURVEY §2.3 D2 sparse
keys, §2.5 iter_libsvm.cc).

TPU-native: the FM score uses the O(N·K) identity
``½[(Xv)² − X²v²]`` with CSR×dense products on the BCOO path; gradients
w.r.t. w and v land only on rows with nonzeros, and ``FMModel.step``
routes them through kvstore ``push``/``row_sparse_pull`` as
``RowSparseNDArray``s — the exact push/pull shape the reference's dist
PS path carries.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["FMModel"]


class FMModel:
    """y = w0 + X·w + ½ Σ_f [(X·v)² − X²·v²]  with sparse X (CSR).

    Parameters live in a kvstore (default ``local``) under keys
    ``fm_w0/fm_w/fm_v`` — ``step`` pushes row_sparse grads and pulls back
    only the touched rows (``row_sparse_pull``), matching the reference's
    embedding-style PS traffic."""

    def __init__(self, num_features, factor_dim=8, lr=0.01, kvstore=None,
                 seed=0):
        from .. import kvstore as kvs
        from .. import ndarray as nd

        rng = np.random.RandomState(seed)
        self.n = num_features
        self.k = factor_dim
        self.lr = lr
        self.w0 = nd.zeros((1,))
        self.w = nd.zeros((num_features, 1))
        self.v = NDArray(rng.normal(0, 0.05,
                                    (num_features, factor_dim))
                         .astype(np.float32))
        self.kv = kvs.create(kvstore) if isinstance(kvstore, str) \
            else (kvstore or kvs.create("local"))
        self.kv.init("fm_w0", self.w0)
        self.kv.init("fm_w", self.w)
        self.kv.init("fm_v", self.v)

    # -- forward --------------------------------------------------------------
    def _score_parts(self, csr):
        from ..ndarray import sparse as sp

        xv = sp.dot(csr, self.v)                     # (B, K)
        x2 = self._square_csr(csr)
        x2v2 = sp.dot(x2, self.v * self.v)           # (B, K)
        linear = sp.dot(csr, self.w)                 # (B, 1)
        return xv, x2v2, linear, x2

    def _logits(self, xv, x2v2, linear):
        from .. import ndarray as nd

        inter = 0.5 * nd.sum(xv * xv - x2v2, axis=1, keepdims=True)
        return self.w0 + linear + inter              # (B, 1)

    @staticmethod
    def _square_csr(csr):
        from ..ndarray import sparse as sp

        return sp.CSRNDArray(csr.data * csr.data, csr.indices, csr.indptr,
                             csr.shape)

    def forward(self, csr):
        xv, x2v2, linear, _x2 = self._score_parts(csr)
        return self._logits(xv, x2v2, linear)        # (B, 1) logits

    __call__ = forward

    # -- manual grads (logistic loss), row-sparse by construction -------------
    def step(self, csr, labels):
        """One logistic-regression FM step on a CSR batch; returns loss.
        Gradients for w/v are RowSparseNDArrays over the batch's feature
        rows, pushed + pulled through the kvstore."""
        from .. import ndarray as nd
        from ..ndarray import sparse as sp

        b = csr.shape[0]
        xv, x2v2, linear, x2 = self._score_parts(csr)  # computed ONCE
        logits = self._logits(xv, x2v2, linear)
        y = labels.reshape((b, 1))
        p = nd.sigmoid(logits)
        # dL/dlogit for mean logistic loss with labels in {0,1}
        dlogit = (p - y) / b                          # (B, 1)
        loss = -nd.mean(y * nd.log(p + 1e-12)
                        + (1 - y) * nd.log(1 - p + 1e-12))

        # grads: w0 ← Σ dlogit; w ← Xᵀ dlogit; v ← Xᵀ(dlogit·Xv) − X²ᵀdlogit·v
        g_w0 = nd.sum(dlogit).reshape((1,))
        g_w_dense = sp.dot(csr, dlogit, transpose_a=True)   # (N, 1)
        t1 = sp.dot(csr, dlogit * xv, transpose_a=True)     # (N, K)
        t2 = sp.dot(x2, dlogit, transpose_a=True) * self.v  # (N, K)
        g_v_dense = t1 - t2

        rows = self._touched_rows(csr)
        g_w = self._rowslice(g_w_dense, rows)
        g_v = self._rowslice(g_v_dense, rows)

        if getattr(self.kv, "_updater", None) is not None:
            # PS round trip (update_on_kvstore): push row_sparse grads,
            # server-side optimizer updates, pull back only touched rows
            self.kv.push("fm_w0", g_w0)
            self.kv.push("fm_w", g_w)
            self.kv.push("fm_v", g_v)
            self.kv.row_sparse_pull("fm_w", out=self.w, row_ids=rows)
            self.kv.row_sparse_pull("fm_v", out=self.v, row_ids=rows)
            self.kv.pull("fm_w0", out=self.w0)
        else:
            # no server optimizer: local SGD (pushing grads would REPLACE
            # the stored weights — reference local stores behave the same)
            self._local_sgd(g_w0, g_w, g_v, rows)
        return float(loss.asscalar())

    @staticmethod
    def _touched_rows(csr):
        from .. import ndarray as nd

        return NDArray(np.unique(np.asarray(csr.indices._data)))

    @staticmethod
    def _rowslice(dense, rows):
        from ..ndarray import sparse as sp

        idx = rows._data.astype(np.int32)
        return sp.RowSparseNDArray(NDArray(dense._data[idx]), rows,
                                   dense.shape)

    def _local_sgd(self, g_w0, g_w, g_v, rows):
        idx = rows._data.astype(np.int32)
        self.w0._data = self.w0._data - self.lr * g_w0._data
        self.w._data = self.w._data.at[idx].add(
            -self.lr * g_w.data._data)
        self.v._data = self.v._data.at[idx].add(
            -self.lr * g_v.data._data)

    # -- evaluation -----------------------------------------------------------
    def accuracy(self, csr, labels):
        from .. import ndarray as nd

        pred = (nd.sigmoid(self.forward(csr)) > 0.5).reshape((-1,))
        return float(nd.mean(pred == labels).asscalar())
