"""Transformer building blocks + encoder-decoder MT model.

Reference: GluonNLP's ``gluonnlp/model/transformer.py:?`` (sibling repo of
the reference — BASELINE config 3 "Transformer-MT") built on the contrib
attention ops (src/operator/contrib/transformer.cc:?).

TPU-native: attention goes through the fused ``dot_product_attention`` op
(flash path on TPU), LayerNorm/FFN through the standard op library so the
whole layer fuses under hybridize; shapes are (B, T, C) throughout with
static sequence lengths (XLA-friendly).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["MultiHeadAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerDecoderCell",
           "TransformerEncoder", "TransformerDecoder", "Transformer",
           "positional_encoding"]


def positional_encoding(length, units, dtype=np.float32):
    """Sinusoidal position table (B-agnostic, (1, T, C))."""
    position = np.arange(length)[:, None].astype(np.float64)
    div = np.exp(np.arange(0, units, 2) * (-np.log(10000.0) / units))
    table = np.zeros((length, units))
    table[:, 0::2] = np.sin(position * div)
    table[:, 1::2] = np.cos(position * div[:units // 2 + units % 2][
        :table[:, 1::2].shape[1]])
    from ..ndarray import NDArray

    return NDArray(table[None].astype(dtype))


class MultiHeadAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads != 0:
            raise MXNetError(
                f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        with self.name_scope():
            self.proj_query = nn.Dense(units, use_bias=use_bias,
                                       flatten=False, prefix="query_")
            self.proj_key = nn.Dense(units, use_bias=use_bias,
                                     flatten=False, prefix="key_")
            self.proj_value = nn.Dense(units, use_bias=use_bias,
                                       flatten=False, prefix="value_")
            self.proj_out = nn.Dense(units, use_bias=use_bias,
                                     flatten=False, prefix="out_")

    def hybrid_forward(self, F, query, key, value, mask=None):
        b = query.shape[0]
        h = self._num_heads
        d = self._units // h
        q = self.proj_query(query).reshape((b, -1, h, d))
        k = self.proj_key(key).reshape((b, -1, h, d))
        v = self.proj_value(value).reshape((b, -1, h, d))
        out = F.dot_product_attention(q, k, v, mask=mask)
        out = out.reshape((b, -1, self._units))
        out = self.proj_out(out)
        if self._dropout:
            out = F.dropout(out, p=self._dropout)
        return out


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="relu",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  activation=activation, prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
        self._dropout = dropout

    def hybrid_forward(self, F, x):
        out = self.ffn_2(self.ffn_1(x))
        if self._dropout:
            out = F.dropout(out, p=self._dropout)
        return out


class TransformerEncoderCell(HybridBlock):
    """Post-norm encoder layer (the reference-era arrangement)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="relu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads, dropout)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation)
            self.layer_norm_att = nn.LayerNorm(in_channels=units)
            self.layer_norm_ffn = nn.LayerNorm(in_channels=units)
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None):
        att = self.attention(x, x, x, mask)
        x = self.layer_norm_att(x + att)
        out = self.ffn(x)
        return self.layer_norm_ffn(x + out)


class TransformerDecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="relu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.self_attention = MultiHeadAttention(units, num_heads,
                                                     dropout)
            self.cross_attention = MultiHeadAttention(units, num_heads,
                                                      dropout)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation)
            self.ln_self = nn.LayerNorm(in_channels=units)
            self.ln_cross = nn.LayerNorm(in_channels=units)
            self.ln_ffn = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory, self_mask=None, mem_mask=None):
        att = self.self_attention(x, x, x, self_mask)
        x = self.ln_self(x + att)
        att = self.cross_attention(x, memory, memory, mem_mask)
        x = self.ln_cross(x + att)
        return self.ln_ffn(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, max_length=512, dropout=0.1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._max_length = max_length
        self._dropout = dropout
        self._pos = positional_encoding(max_length, units)
        with self.name_scope():
            self.cells = nn.HybridSequential(prefix="")
            for _ in range(num_layers):
                self.cells.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout))

    def hybrid_forward(self, F, x, mask=None):
        t = x.shape[1]
        x = x * np.sqrt(self._units) + self._pos[:, :t].astype(x.dtype)
        if self._dropout:
            x = F.dropout(x, p=self._dropout)
        for cell in self.cells:
            x = cell(x, mask)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, max_length=512, dropout=0.1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._dropout = dropout
        self._pos = positional_encoding(max_length, units)
        with self.name_scope():
            self.cells = nn.HybridSequential(prefix="")
            for _ in range(num_layers):
                self.cells.add(TransformerDecoderCell(
                    units, hidden_size, num_heads, dropout))

    def hybrid_forward(self, F, x, memory, self_mask=None, mem_mask=None):
        t = x.shape[1]
        x = x * np.sqrt(self._units) + self._pos[:, :t].astype(x.dtype)
        if self._dropout:
            x = F.dropout(x, p=self._dropout)
        for cell in self.cells:
            x = cell(x, memory, self_mask, mem_mask)
        return x


def _causal_mask(F, t, batch):
    import jax.numpy as jnp
    from ..ndarray import NDArray

    m = np.tril(np.ones((t, t), bool))[None, None]
    return NDArray(np.broadcast_to(m, (batch, 1, t, t)).copy())


class Transformer(HybridBlock):
    """Encoder-decoder MT transformer (reference: GluonNLP
    ``transformer_en_de_512`` config shape)."""

    def __init__(self, src_vocab_size, tgt_vocab_size, num_layers=6,
                 units=512, hidden_size=2048, num_heads=8, max_length=512,
                 dropout=0.1, share_embed=False, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab_size, units)
            if share_embed and src_vocab_size == tgt_vocab_size:
                self.tgt_embed = self.src_embed
            else:
                self.tgt_embed = nn.Embedding(tgt_vocab_size, units)
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, max_length,
                dropout)
            self.decoder = TransformerDecoder(
                num_layers, units, hidden_size, num_heads, max_length,
                dropout)
            self.proj = nn.Dense(tgt_vocab_size, flatten=False,
                                 prefix="proj_")

    def encode(self, src, src_mask=None):
        return self.encoder(self.src_embed(src), src_mask)

    def decode(self, tgt, memory, self_mask=None, mem_mask=None):
        return self.proj(self.decoder(self.tgt_embed(tgt), memory,
                                      self_mask, mem_mask))

    def hybrid_forward(self, F, src, tgt):
        memory = self.encode(src)
        causal = _causal_mask(F, tgt.shape[1], tgt.shape[0])
        return self.decode(tgt, memory, causal)
