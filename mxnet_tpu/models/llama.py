"""Llama model family (stretch config 5 in BASELINE.md).

Reference: NONE — the reference predates Llama (SURVEY §5 long-context:
ABSENT).  This is new capability, built the way the reference's GluonNLP
zoo would have shipped it: config-driven Gluon HybridBlocks, so a stock
``gluon.Trainer`` trains it and ``hybridize()`` compiles one XLA program.

TPU-first design:
- attention runs the Pallas flash kernel (ops/flash_attention.py) when on
  TPU — O(T·D) HBM traffic; ring/Ulysses sequence parallelism plugs in via
  ``attn_mode`` for long context (parallel/ring.py over the ICI mesh);
- GQA: KV heads repeated at compute time (bf16-friendly, keeps the KV
  projection narrow the way Llama-3 does);
- RoPE is precomputed per (T, D) and baked into the trace as constants;
- weights are all ``use_bias=False`` Dense layers → pure MXU matmuls, and
  ``shard_llama`` annotates tp/dp shardings for pjit (megatron-style
  column/row split pairs).
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["LlamaConfig", "RMSNorm", "LlamaAttention", "LlamaMLP",
           "LlamaDecoderLayer", "LlamaModel", "LlamaForCausalLM",
           "llama3_8b", "llama_tiny", "mixtral_8x7b", "mixtral_tiny",
           "shard_llama", "LLAMA_CONFIGS"]


class LlamaConfig:
    def __init__(self, hidden_size=4096, intermediate_size=14336,
                 num_layers=32, num_heads=32, num_kv_heads=8,
                 vocab_size=128256, max_seq_len=8192, rope_theta=500000.0,
                 rms_eps=1e-5, tie_embeddings=False, attn_mode="flash",
                 num_experts=0, num_experts_per_tok=2,
                 capacity_factor=1.25, moe_router="topk"):
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads
        self.vocab_size = vocab_size
        self.max_seq_len = max_seq_len
        self.rope_theta = rope_theta
        self.rms_eps = rms_eps
        self.tie_embeddings = tie_embeddings
        self.attn_mode = attn_mode  # flash | sdpa | ring | ulysses
        # MoE (Mixtral-style): 0 experts = dense SwiGLU MLP
        self.num_experts = num_experts
        self.num_experts_per_tok = num_experts_per_tok
        self.capacity_factor = capacity_factor
        # topk | expert_choice — see models/moe.py: expert_choice leaks
        # future-token info in causal decoders; topk for production LM
        self.moe_router = moe_router
        if hidden_size % num_heads:
            raise MXNetError("num_heads must evenly divide hidden_size")
        if num_heads % num_kv_heads:
            raise MXNetError("num_kv_heads must evenly divide num_heads")
        self.head_dim = hidden_size // num_heads


LLAMA_CONFIGS = {
    "llama3_8b": dict(hidden_size=4096, intermediate_size=14336,
                      num_layers=32, num_heads=32, num_kv_heads=8,
                      vocab_size=128256, rope_theta=500000.0),
    "llama_tiny": dict(hidden_size=64, intermediate_size=176,
                       num_layers=2, num_heads=4, num_kv_heads=2,
                       vocab_size=256, max_seq_len=128),
    # Mixtral-8x7B architecture (sparse MoE decoder, top-2 of 8 experts)
    "mixtral_8x7b": dict(hidden_size=4096, intermediate_size=14336,
                         num_layers=32, num_heads=32, num_kv_heads=8,
                         vocab_size=32000, rope_theta=1e6,
                         num_experts=8, num_experts_per_tok=2),
    "mixtral_tiny": dict(hidden_size=64, intermediate_size=176,
                         num_layers=2, num_heads=4, num_kv_heads=2,
                         vocab_size=256, max_seq_len=128,
                         num_experts=4, num_experts_per_tok=2),
}


class RMSNorm(HybridBlock):
    """Root-mean-square LayerNorm (no mean subtraction, no bias); stats in
    fp32 even under bf16 params."""

    def __init__(self, units, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units,),
                                          init="ones")

    def hybrid_forward(self, F, x, weight):
        from ..ops.registry import apply_op
        import jax.numpy as jnp

        def _f(xr, wr):
            xf = xr.astype(jnp.float32)
            var = (xf * xf).mean(axis=-1, keepdims=True)
            out = xf / jnp.sqrt(var + self._eps)
            return (out * wr.astype(jnp.float32)).astype(xr.dtype)

        return apply_op(_f, x, weight, name="rms_norm")


def _rope_tables(t, head_dim, theta):
    """cos/sin tables (T, head_dim/2) — compile-time constants."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2,
                                     dtype=np.float64) / head_dim))
    pos = np.arange(t, dtype=np.float64)
    ang = np.outer(pos, inv)
    return (np.cos(ang).astype(np.float32),
            np.sin(ang).astype(np.float32))


def _apply_rope(x, cos, sin):
    """x (B, H, T, D) with D even; rotate pairs (x[..., ::2], x[..., 1::2])."""
    import jax.numpy as jnp

    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    xr1 = x1 * cos - x2 * sin
    xr2 = x1 * sin + x2 * cos
    out = jnp.stack([xr1, xr2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(HybridBlock):
    """GQA self-attention with RoPE + flash kernel."""

    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        hd = cfg.head_dim
        with self.name_scope():
            self.q_proj = nn.Dense(cfg.num_heads * hd, use_bias=False,
                                   flatten=False, in_units=cfg.hidden_size,
                                   prefix="q_")
            self.k_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=cfg.hidden_size,
                                   prefix="k_")
            self.v_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=cfg.hidden_size,
                                   prefix="v_")
            self.o_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                   flatten=False,
                                   in_units=cfg.num_heads * hd, prefix="o_")
        self._rope_cache = {}

    def _rope(self, t):
        if t not in self._rope_cache:
            import jax.numpy as jnp

            cos, sin = _rope_tables(t, self._cfg.head_dim,
                                    self._cfg.rope_theta)
            self._rope_cache[t] = (jnp.asarray(cos), jnp.asarray(sin))
        return self._rope_cache[t]

    def hybrid_forward(self, F, x, **params):
        from ..ops.registry import apply_op

        cfg = self._cfg
        b, t = x.shape[0], x.shape[1]
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        cos, sin = self._rope(t)

        def _attend(qr, kr, vr):
            import jax.numpy as jnp

            hd = cfg.head_dim
            qh = qr.reshape(b, t, cfg.num_heads, hd).transpose(0, 2, 1, 3)
            kh = kr.reshape(b, t, cfg.num_kv_heads, hd) \
                .transpose(0, 2, 1, 3)
            vh = vr.reshape(b, t, cfg.num_kv_heads, hd) \
                .transpose(0, 2, 1, 3)
            qh = _apply_rope(qh, cos[None, None], sin[None, None])
            kh = _apply_rope(kh, cos[None, None], sin[None, None])
            rep = cfg.num_heads // cfg.num_kv_heads
            if rep > 1:
                kh = jnp.repeat(kh, rep, axis=1)
                vh = jnp.repeat(vh, rep, axis=1)
            if cfg.attn_mode in ("ring", "ulysses"):
                from ..parallel import ring as _ring

                fn = (_ring.ring_attention_raw
                      if cfg.attn_mode == "ring"
                      else _ring.ulysses_attention_raw)
                out = fn(qh, kh, vh, causal=True,
                         scale=1.0 / math.sqrt(hd))
            elif cfg.attn_mode == "flash":
                from ..ops.flash_attention import flash_attention_raw

                out = flash_attention_raw(qh, kh, vh, True,
                                          1.0 / math.sqrt(hd))
            else:
                from ..ops.flash_attention import _sdpa_ref

                out = _sdpa_ref(qh, kh, vh, True, 1.0 / math.sqrt(hd))
            return out.transpose(0, 2, 1, 3).reshape(b, t, -1)

        ctx = apply_op(_attend, q, k, v, name="llama_attention")
        return self.o_proj(ctx)


class LlamaMLP(HybridBlock):
    """SwiGLU feed-forward: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.gate_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                      flatten=False,
                                      in_units=cfg.hidden_size,
                                      prefix="gate_")
            self.up_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                    flatten=False, in_units=cfg.hidden_size,
                                    prefix="up_")
            self.down_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                      flatten=False,
                                      in_units=cfg.intermediate_size,
                                      prefix="down_")

    def hybrid_forward(self, F, x):
        g = self.gate_proj(x)
        return self.down_proj(g * F.sigmoid(g) * self.up_proj(x))


class LlamaDecoderLayer(HybridBlock):
    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.input_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps,
                                           prefix="ln_in_")
            self.self_attn = LlamaAttention(cfg, prefix="attn_")
            self.post_attention_layernorm = RMSNorm(
                cfg.hidden_size, cfg.rms_eps, prefix="ln_post_")
            if cfg.num_experts > 0:
                from .moe import MoEMLP

                self.mlp = MoEMLP(cfg.hidden_size, cfg.intermediate_size,
                                  cfg.num_experts, cfg.num_experts_per_tok,
                                  cfg.capacity_factor, cfg.moe_router,
                                  prefix="moe_")
            else:
                self.mlp = LlamaMLP(cfg, prefix="mlp_")

    def hybrid_forward(self, F, x):
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(HybridBlock):
    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.embed_tokens = nn.Embedding(cfg.vocab_size,
                                             cfg.hidden_size,
                                             prefix="embed_")
            self.layers = nn.HybridSequential(prefix="layers_")
            for _ in range(cfg.num_layers):
                self.layers.add(LlamaDecoderLayer(cfg))
            self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps,
                                prefix="norm_")

    def hybrid_forward(self, F, input_ids):
        h = self.embed_tokens(input_ids)
        for layer in self.layers:
            h = layer(h)
        return self.norm(h)


class LlamaForCausalLM(HybridBlock):
    """Decoder + LM head; training forward returns logits (B, T, V)."""

    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.model = LlamaModel(cfg, prefix="model_")
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                    flatten=False,
                                    in_units=cfg.hidden_size,
                                    prefix="lm_head_")

    @property
    def config(self):
        return self._cfg

    def hybrid_forward(self, F, input_ids):
        h = self.model(input_ids)
        if self._cfg.tie_embeddings:
            from ..ops.registry import apply_op

            w = self.model.embed_tokens.weight.data()
            return apply_op(lambda hr, wr: hr @ wr.T, h, w,
                            name="tied_lm_head")
        return self.lm_head(h)

    def generate(self, input_ids, max_new_tokens=16):
        """Greedy decoding (no KV cache — full re-forward per token; a
        cached incremental path is future work)."""
        from .. import ndarray as nd
        from .. import autograd as ag

        cur = input_ids
        with ag.pause():
            for _ in range(max_new_tokens):
                logits = self(cur)
                nxt = nd.argmax(logits, axis=-1)[:, -1:]
                cur = nd.concat(cur, nxt.astype(cur.dtype), dim=1)
        return cur


def llama3_8b(**overrides):
    """Llama-3-8B architecture (BASELINE config 5)."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["llama3_8b"],
                                           **overrides}))


def llama_tiny(**overrides):
    """Tiny config for tests/dryruns."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["llama_tiny"],
                                           **overrides}))


def mixtral_8x7b(**overrides):
    """Mixtral-8x7B sparse-MoE architecture (beyond-reference model
    family: MoE + expert parallelism, SURVEY §2.3 D9)."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["mixtral_8x7b"],
                                           **overrides}))


def mixtral_tiny(**overrides):
    """Tiny MoE config for tests/dryruns."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["mixtral_tiny"],
                                           **overrides}))


def shard_llama(net, mesh=None, tp_axis="tp", dp_axis="dp", ep_axis="ep"):
    """Annotate megatron-style TP shardings over ``mesh`` (pjit/GSPMD
    derives the collectives — SURVEY §2.3 D6, new capability):

    - q/k/v/gate/up: column-parallel (output dim split over tp)
    - o/down:       row-parallel (input dim split over tp)
    - embed/lm_head: vocab-parallel
    - MoE layers: expert bank sharded over ``ep`` (+tp within experts)
    Replicates everything else.  Weights are stored (out, in), so the
    output dim is axis 0.
    """
    from .. import parallel
    from .moe import MoEMLP, shard_moe

    mesh = mesh or parallel.current_mesh()
    has_tp = mesh is not None and tp_axis in mesh.shape
    has_ep = mesh is not None and ep_axis in mesh.shape
    if mesh is None or not (has_tp or has_ep):
        parallel.replicate_block_params(net)
        return net
    col = (tp_axis, None)
    row = (None, tp_axis)
    parallel.replicate_block_params(net)  # baseline: replicate all
    for layer in net.model.layers:
        attn, mlp = layer.self_attn, layer.mlp
        if has_tp:
            for p in (attn.q_proj.weight, attn.k_proj.weight,
                      attn.v_proj.weight):
                parallel.shard_param(p, col, mesh)
            parallel.shard_param(attn.o_proj.weight, row, mesh)
        if isinstance(mlp, MoEMLP):
            shard_moe(mlp, mesh, ep_axis=ep_axis,
                      tp_axis=tp_axis if has_tp else None)
        elif has_tp:
            for p in (mlp.gate_proj.weight, mlp.up_proj.weight):
                parallel.shard_param(p, col, mesh)
            parallel.shard_param(mlp.down_proj.weight, row, mesh)
    if has_tp:
        parallel.shard_param(net.model.embed_tokens.weight, col, mesh)
        if not net._cfg.tie_embeddings:
            parallel.shard_param(net.lm_head.weight, col, mesh)
    return net
