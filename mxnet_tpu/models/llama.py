"""Llama model family (stretch config 5 in BASELINE.md).

Reference: NONE — the reference predates Llama (SURVEY §5 long-context:
ABSENT).  This is new capability, built the way the reference's GluonNLP
zoo would have shipped it: config-driven Gluon HybridBlocks, so a stock
``gluon.Trainer`` trains it and ``hybridize()`` compiles one XLA program.

TPU-first design:
- attention runs the Pallas flash kernel (ops/flash_attention.py) when on
  TPU — O(T·D) HBM traffic; ring/Ulysses sequence parallelism plugs in via
  ``attn_mode`` for long context (parallel/ring.py over the ICI mesh);
- GQA: KV heads repeated at compute time (bf16-friendly, keeps the KV
  projection narrow the way Llama-3 does);
- RoPE is precomputed per (T, D) and baked into the trace as constants;
- weights are all ``use_bias=False`` Dense layers → pure MXU matmuls, and
  ``shard_llama`` annotates tp/dp shardings for pjit (megatron-style
  column/row split pairs).
"""
from __future__ import annotations

import math

import numpy as np

from .. import autograd
from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn
from ..telemetry import numerics as _numerics

__all__ = ["LlamaConfig", "RMSNorm", "LlamaAttention", "LlamaMLP",
           "LlamaDecoderLayer", "LlamaModel", "LlamaForCausalLM",
           "LlamaDecoder", "llama3_8b", "llama_tiny", "mixtral_8x7b",
           "mixtral_tiny", "shard_llama", "llama_param_pspecs",
           "llama_pipeline_forward", "llama_pipeline_train_step",
           "packed_lm_loss", "LLAMA_CONFIGS"]


class LlamaConfig:
    def __init__(self, hidden_size=4096, intermediate_size=14336,
                 num_layers=32, num_heads=32, num_kv_heads=8,
                 vocab_size=128256, max_seq_len=8192, rope_theta=500000.0,
                 rms_eps=1e-5, tie_embeddings=False, attn_mode="flash",
                 num_experts=0, num_experts_per_tok=2,
                 capacity_factor=1.25, moe_router="topk",
                 scan_layers=False):
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads
        self.vocab_size = vocab_size
        self.max_seq_len = max_seq_len
        self.rope_theta = rope_theta
        self.rms_eps = rms_eps
        self.tie_embeddings = tie_embeddings
        self.attn_mode = attn_mode  # flash | sdpa | ring | ulysses
        # MoE (Mixtral-style): 0 experts = dense SwiGLU MLP
        self.num_experts = num_experts
        self.num_experts_per_tok = num_experts_per_tok
        self.capacity_factor = capacity_factor
        # topk | expert_choice — see models/moe.py: expert_choice leaks
        # future-token info in causal decoders; topk for production LM
        self.moe_router = moe_router
        # scan_layers: trace/compile ONE decoder layer and lax.scan it
        # over a stacked parameter tree (the production TPU idiom —
        # layer-count-independent compile time, per-layer buffers
        # allocated once, per-iteration remat).  Cost: one recorded
        # restack of the layer parameters per step (an extra HBM pass
        # over the weights); leave False when squeezing the last GiB on
        # a single chip.  r4 scale-proof finding, tools/scale_proof.py.
        self.scan_layers = scan_layers
        if hidden_size % num_heads:
            raise MXNetError("num_heads must evenly divide hidden_size")
        if num_heads % num_kv_heads:
            raise MXNetError("num_kv_heads must evenly divide num_heads")
        self.head_dim = hidden_size // num_heads

#: reviewed signature budget (mxlint T15): the scanned-layer machinery
#: compiles one stacked-layer program per (model config, batch avals,
#: remat policy) — layer homogeneity is the point of the scan, so the
#: per-layer axis contributes no signatures
__compile_signatures__ = {
    "llama_scan": "1 per (model config, batch avals, remat policy)",
}

LLAMA_CONFIGS = {
    "llama3_8b": dict(hidden_size=4096, intermediate_size=14336,
                      num_layers=32, num_heads=32, num_kv_heads=8,
                      vocab_size=128256, rope_theta=500000.0),
    "llama_tiny": dict(hidden_size=64, intermediate_size=176,
                       num_layers=2, num_heads=4, num_kv_heads=2,
                       vocab_size=256, max_seq_len=128),
    # Mixtral-8x7B architecture (sparse MoE decoder, top-2 of 8 experts)
    "mixtral_8x7b": dict(hidden_size=4096, intermediate_size=14336,
                         num_layers=32, num_heads=32, num_kv_heads=8,
                         vocab_size=32000, rope_theta=1e6,
                         num_experts=8, num_experts_per_tok=2),
    "mixtral_tiny": dict(hidden_size=64, intermediate_size=176,
                         num_layers=2, num_heads=4, num_kv_heads=2,
                         vocab_size=256, max_seq_len=128,
                         num_experts=4, num_experts_per_tok=2),
}


class RMSNorm(HybridBlock):
    """Root-mean-square LayerNorm (no mean subtraction, no bias); stats in
    fp32 even under bf16 params."""

    def __init__(self, units, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units,),
                                          init="ones")

    def hybrid_forward(self, F, x, weight):
        from ..ops.registry import apply_op
        import jax.numpy as jnp

        def _f(xr, wr):
            xf = xr.astype(jnp.float32)
            var = (xf * xf).mean(axis=-1, keepdims=True)
            out = xf / jnp.sqrt(var + self._eps)
            return (out * wr.astype(jnp.float32)).astype(xr.dtype)

        return apply_op(_f, x, weight, name="rms_norm")


def _rope_tables(t, head_dim, theta):
    """cos/sin tables (T, head_dim/2) — compile-time constants."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2,
                                     dtype=np.float64) / head_dim))
    pos = np.arange(t, dtype=np.float64)
    ang = np.outer(pos, inv)
    return (np.cos(ang).astype(np.float32),
            np.sin(ang).astype(np.float32))


def _apply_rope(x, cos, sin):
    """x (B, H, T, D) with D even; rotate pairs (x[..., ::2], x[..., 1::2])."""
    import jax.numpy as jnp

    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    xr1 = x1 * cos - x2 * sin
    xr2 = x1 * sin + x2 * cos
    out = jnp.stack([xr1, xr2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(HybridBlock):
    """GQA self-attention with RoPE + flash kernel."""

    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        hd = cfg.head_dim
        with self.name_scope():
            self.q_proj = nn.Dense(cfg.num_heads * hd, use_bias=False,
                                   flatten=False, in_units=cfg.hidden_size,
                                   prefix="q_")
            self.k_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=cfg.hidden_size,
                                   prefix="k_")
            self.v_proj = nn.Dense(cfg.num_kv_heads * hd, use_bias=False,
                                   flatten=False, in_units=cfg.hidden_size,
                                   prefix="v_")
            self.o_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                   flatten=False,
                                   in_units=cfg.num_heads * hd, prefix="o_")
        self._rope_cache = {}

    def _rope(self, t):
        # cache the NUMPY tables, never device arrays: jnp.asarray
        # under an active trace stages a constant owned by THAT trace,
        # and caching it leaks a stale tracer into the next retrace
        # (e.g. when the scan machinery rebuilds for a new remat tier)
        if t not in self._rope_cache:
            self._rope_cache[t] = _rope_tables(t, self._cfg.head_dim,
                                               self._cfg.rope_theta)
        import jax.numpy as jnp

        cos, sin = self._rope_cache[t]
        return jnp.asarray(cos), jnp.asarray(sin)

    def hybrid_forward(self, F, x, segment_ids=None, **params):
        from ..ops.registry import apply_op

        cfg = self._cfg
        b, t = x.shape[0], x.shape[1]
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        cos, sin = self._rope(t)

        def _heads(qr, kr, vr):
            import jax.numpy as jnp

            hd = cfg.head_dim
            qh = qr.reshape(b, t, cfg.num_heads, hd).transpose(0, 2, 1, 3)
            kh = kr.reshape(b, t, cfg.num_kv_heads, hd) \
                .transpose(0, 2, 1, 3)
            vh = vr.reshape(b, t, cfg.num_kv_heads, hd) \
                .transpose(0, 2, 1, 3)
            qh = _apply_rope(qh, cos[None, None], sin[None, None])
            kh = _apply_rope(kh, cos[None, None], sin[None, None])
            rep = cfg.num_heads // cfg.num_kv_heads
            if rep > 1:
                kh = jnp.repeat(kh, rep, axis=1)
                vh = jnp.repeat(vh, rep, axis=1)
            return qh, kh, vh

        def _attend(qr, kr, vr):
            qh, kh, vh = _heads(qr, kr, vr)
            hd = cfg.head_dim
            if cfg.attn_mode in ("ring", "ulysses"):
                from ..parallel import ring as _ring

                fn = (_ring.ring_attention_raw
                      if cfg.attn_mode == "ring"
                      else _ring.ulysses_attention_raw)
                out = fn(qh, kh, vh, causal=True,
                         scale=1.0 / math.sqrt(hd))
            elif cfg.attn_mode == "flash":
                from ..ops.flash_attention import flash_attention_raw

                out = flash_attention_raw(qh, kh, vh, True,
                                          1.0 / math.sqrt(hd))
            else:
                from ..ops.flash_attention import _sdpa_ref

                out = _sdpa_ref(qh, kh, vh, True, 1.0 / math.sqrt(hd))
            return out.transpose(0, 2, 1, 3).reshape(b, t, -1)

        def _attend_packed(qr, kr, vr, segr):
            # packed-batch path: causal AND same-segment, the serving
            # slots' mask shape (LlamaDecoder._attend) applied to
            # training.  Flash/ring modes have no segment support, so
            # packing always takes the dense masked sdpa.
            qh, kh, vh = _heads(qr, kr, vr)
            out = _sdpa_segmented(qh, kh, vh, segr,
                                  1.0 / math.sqrt(cfg.head_dim))
            return out.transpose(0, 2, 1, 3).reshape(b, t, -1)

        if segment_ids is not None:
            ctx = apply_op(_attend_packed, q, k, v, segment_ids,
                           name="llama_attention_packed")
        else:
            ctx = apply_op(_attend, q, k, v, name="llama_attention")
        return self.o_proj(ctx)


def _segment_causal_mask(seg):
    """(B, T) int segment ids → (B, 1, T, T) bool attention mask:
    causal AND same-segment, the packed-batch analogue of the per-slot
    mask the serving step builds in ``LlamaDecoder._attend``.  The
    diagonal is always legal (``seg[q] == seg[q]``), so no query row is
    fully masked and the dense softmax stays NaN-free even on padding
    rows (segment id 0); padding positions only see other padding and
    their loss is masked out anyway (``data.PackedBatch.loss_mask``)."""
    import jax.numpy as jnp

    seg = seg.astype(jnp.int32)
    t = seg.shape[1]
    same = seg[:, :, None] == seg[:, None, :]
    causal = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
    return (same & causal[None])[:, None]


def _sdpa_segmented(q, k, v, seg, scale):
    """Dense sdpa with the segment-causal mask — f32 score accumulation
    like ``_sdpa_ref``/the serving ``_attend``.  q/k/v (B, H, T, D)
    post-GQA-repeat, seg (B, T) int."""
    import jax
    import jax.numpy as jnp

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(_segment_causal_mask(seg), s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


class LlamaMLP(HybridBlock):
    """SwiGLU feed-forward: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.gate_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                      flatten=False,
                                      in_units=cfg.hidden_size,
                                      prefix="gate_")
            self.up_proj = nn.Dense(cfg.intermediate_size, use_bias=False,
                                    flatten=False, in_units=cfg.hidden_size,
                                    prefix="up_")
            self.down_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                      flatten=False,
                                      in_units=cfg.intermediate_size,
                                      prefix="down_")

    def hybrid_forward(self, F, x):
        g = self.gate_proj(x)
        return self.down_proj(g * F.sigmoid(g) * self.up_proj(x))


class LlamaDecoderLayer(HybridBlock):
    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.input_layernorm = RMSNorm(cfg.hidden_size, cfg.rms_eps,
                                           prefix="ln_in_")
            self.self_attn = LlamaAttention(cfg, prefix="attn_")
            self.post_attention_layernorm = RMSNorm(
                cfg.hidden_size, cfg.rms_eps, prefix="ln_post_")
            if cfg.num_experts > 0:
                from .moe import MoEMLP

                self.mlp = MoEMLP(cfg.hidden_size, cfg.intermediate_size,
                                  cfg.num_experts, cfg.num_experts_per_tok,
                                  cfg.capacity_factor, cfg.moe_router,
                                  prefix="moe_")
            else:
                self.mlp = LlamaMLP(cfg, prefix="mlp_")

    def hybrid_forward(self, F, x, segment_ids=None):
        if segment_ids is None:
            x = x + self.self_attn(self.input_layernorm(x))
        else:
            x = x + self.self_attn(self.input_layernorm(x), segment_ids)
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(HybridBlock):
    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.embed_tokens = nn.Embedding(cfg.vocab_size,
                                             cfg.hidden_size,
                                             prefix="embed_")
            self.layers = nn.HybridSequential(prefix="layers_")
            for _ in range(cfg.num_layers):
                self.layers.add(LlamaDecoderLayer(cfg))
            self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps,
                                prefix="norm_")

    def hybrid_forward(self, F, input_ids, segment_ids=None):
        h = self.embed_tokens(input_ids)
        _numerics.tap("embed", h)
        if self._cfg.scan_layers and len(self.layers) > 1:
            # per-layer stats exit the scan as stacked ys — taps here
            # would see scan-body tracers; see _scan_machinery
            h = _apply_layers_scanned(self, h, segment_ids)
        else:
            for i, layer in enumerate(self.layers):
                h = layer(h) if segment_ids is None \
                    else layer(h, segment_ids)
                _numerics.tap(f"decoder.{i}", h)
        h = self.norm(h)
        _numerics.tap("norm", h)
        return h


class LlamaForCausalLM(HybridBlock):
    """Decoder + LM head; training forward returns logits (B, T, V)."""

    def __init__(self, cfg: LlamaConfig, **kwargs):
        super().__init__(**kwargs)
        self._cfg = cfg
        with self.name_scope():
            self.model = LlamaModel(cfg, prefix="model_")
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                    flatten=False,
                                    in_units=cfg.hidden_size,
                                    prefix="lm_head_")

    @property
    def config(self):
        return self._cfg

    def hybrid_forward(self, F, input_ids, segment_ids=None):
        """``segment_ids`` (B, T) int — packed-pretraining mode
        (``data.SequencePacker``): attention is masked to causal ∧
        same-segment so packed documents never see each other.  One
        compile signature either way: the packed batch shape is fixed
        by the packer, and segment ids ride as a second traced input,
        not as shape variation."""
        if segment_ids is None:
            h = self.model(input_ids)
        else:
            h = self.model(input_ids, segment_ids)
        out = _lm_head(self, h)
        _numerics.tap("logits", out)
        return out

    def set_remat(self, tier):
        """Set the decoder-stack remat tier ("none" / "dots" / "layer"
        / "auto"; see ``mxnet_tpu.memory.policy``).  "auto" asks the
        planner for the cheapest tier that fits the device budget at
        first forward.  Default is "layer" — the historical blanket
        per-decoder-layer ``jax.checkpoint``.  Rebuilds the scan
        machinery, so the next step retraces."""
        from ..memory import policy as _mem_policy

        self.model._remat = _mem_policy.normalize(tier)
        self.model._scan_mach = None
        return self

    def generate(self, input_ids, max_new_tokens=16, use_cache=True,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 seed=None):
        """Decoding.  ``use_cache=True`` (default) runs the jitted
        incremental decode step with a static-shape KV cache
        (O(T) per token); ``use_cache=False`` re-forwards the full
        sequence per token (O(T²), kept as the greedy reference oracle).
        ``do_sample=True`` draws from the (temperature / top-k / top-p
        filtered) distribution — cached path only."""
        from .. import ndarray as nd
        from .. import autograd as ag

        # guard BOTH paths (cached and oracle/MoE): positions past
        # max_seq_len mean RoPE extrapolation outside the trained window
        need = input_ids.shape[1] + max_new_tokens
        max_ctx = getattr(self._cfg, "max_seq_len", None)
        if max_ctx is not None and need > max_ctx:
            raise MXNetError(
                f"generate: prompt ({input_ids.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) = {need} exceeds the model's "
                f"max_seq_len ({max_ctx}); RoPE tables and KV caches are "
                f"only valid inside the trained context window")
        if use_cache and self._cfg.num_experts == 0:
            return self._generate_cached(
                input_ids, max_new_tokens, do_sample=do_sample,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed)
        if do_sample:
            raise MXNetError("do_sample requires the KV-cache path "
                             "(use_cache=True, dense MLP config)")
        cur = input_ids
        with ag.pause():
            for _ in range(max_new_tokens):
                logits = self(cur)
                nxt = nd.argmax(logits, axis=-1)[:, -1:]
                cur = nd.concat(cur, nxt.astype(cur.dtype), dim=1)
        return cur

    def _generate_cached(self, input_ids, max_new_tokens, **sample_kw):
        from .. import ndarray as nd

        if max_new_tokens < 1:  # n=0: prompt unchanged (oracle parity)
            return input_ids
        b, t0 = input_ids.shape
        # bucket max_len to a power of two (min 64) so repeated calls with
        # nearby lengths reuse ONE compiled decoder instead of recompiling
        need = t0 + max_new_tokens  # generate() validated need<=max_seq_len
        max_ctx = getattr(self._cfg, "max_seq_len", None)
        bucket = 64
        while bucket < need:
            bucket *= 2
        if max_ctx is not None:
            bucket = min(bucket, max_ctx)
        cache = self.__dict__.setdefault("_kv_decoders", {})
        dec = cache.get(bucket)
        if dec is None:
            dec = cache[bucket] = LlamaDecoder(self, max_len=bucket)
        ids = dec.generate(input_ids._data, max_new_tokens, **sample_kw)
        return nd.NDArray(ids).astype(input_ids.dtype)


class LlamaDecoder:
    """Jitted incremental decoder with a static-shape KV cache.

    Reference: NONE (the reference predates LLM serving).  TPU-first
    design: ``generate`` is ONE compiled XLA program — a batched
    full-sequence prefill writes the prompt's K/V into the
    (B, Hkv, max_len, D) cache, then a ``lax.scan`` greedy-decode loop
    runs entirely on device (no per-token host round trips).  Weights
    enter as jit ARGUMENTS (pulled fresh from the net's Parameters on
    every call), so generation always sees current weights and XLA does
    not bake multi-GB constants into the executable.

    The math mirrors ``LlamaAttention``/``LlamaMLP``; attention scores
    accumulate in float32 (``preferred_element_type``) exactly like the
    training ``_sdpa_ref`` path, and tests/test_llama.py pins cached ==
    uncached logits so the paths cannot drift.  Dense MLP only (MoE
    decode falls back to the oracle path).
    """

    def __init__(self, net: "LlamaForCausalLM", max_len: int):
        import jax
        import jax.numpy as jnp

        cfg = net.config
        if cfg.num_experts:
            raise MXNetError("LlamaDecoder supports dense MLP configs")
        self.cfg = cfg
        self.max_len = int(max_len)
        self._net = net
        cos, sin = _rope_tables(self.max_len, cfg.head_dim, cfg.rope_theta)
        self._cos, self._sin = jnp.asarray(cos), jnp.asarray(sin)
        self._step = jax.jit(self._step_impl, donate_argnums=(1,))
        self._gen = jax.jit(self._generate_impl,
                            static_argnums=(6, 7, 8, 9))

    def _weights(self):
        """Fresh raw-weight pytree from the net's Parameters (cheap: just
        handle plumbing; jit hashes it by shape/dtype, not value)."""
        net = self._net
        raw = lambda p: p.data()._data  # noqa: E731
        layers = [
            dict(ln_in=raw(lr.input_layernorm.weight),
                 q=raw(lr.self_attn.q_proj.weight),
                 k=raw(lr.self_attn.k_proj.weight),
                 v=raw(lr.self_attn.v_proj.weight),
                 o=raw(lr.self_attn.o_proj.weight),
                 ln_post=raw(lr.post_attention_layernorm.weight),
                 gate=raw(lr.mlp.gate_proj.weight),
                 up=raw(lr.mlp.up_proj.weight),
                 down=raw(lr.mlp.down_proj.weight))
            for lr in net.model.layers]
        emb = raw(net.model.embed_tokens.weight)
        head = emb if self.cfg.tie_embeddings else raw(net.lm_head.weight)
        return dict(layers=layers, emb=emb,
                    norm=raw(net.model.norm.weight), head=head)

    def init_cache(self, batch):
        import jax.numpy as jnp

        cfg = self.cfg
        shape = (batch, cfg.num_kv_heads, self.max_len, cfg.head_dim)
        dt = self._net.model.embed_tokens.weight.data().dtype
        import numpy as np

        dt = np.dtype(dt)
        return [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                for _ in range(cfg.num_layers)]

    @staticmethod
    def _rms(x, w, eps):
        import jax.numpy as jnp

        xf = x.astype(jnp.float32)
        var = (xf * xf).mean(axis=-1, keepdims=True)
        return (xf / jnp.sqrt(var + eps) * w.astype(jnp.float32)) \
            .astype(x.dtype)

    def _attend(self, q, k, v, mask):
        """Scores in f32 accumulation (matches _sdpa_ref), masked
        softmax, context.  q (B,H,Q,D); k/v (B,Hkv,T,D); mask (Q,T)
        shared across the batch, or already broadcastable to
        (B,H,Q,T) — the per-slot serving step masks each batch row at
        its own cache length."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        rep = cfg.num_heads // cfg.num_kv_heads
        if rep > 1:
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        scores = jnp.einsum("bhqd,bhtd->bhqt", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(cfg.head_dim))
        if mask.ndim == 2:
            mask = mask[None, None]
        scores = jnp.where(mask, scores, -jnp.inf)
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqt,bhtd->bhqd", attn, v)

    def _layer(self, L, x, ctx_fn):
        """Shared residual wiring: x + attn(ln(x)) then + mlp(ln(x))."""
        import jax

        cfg = self.cfg
        h = self._rms(x, L["ln_in"], cfg.rms_eps)
        x = x + ctx_fn(h)
        h2 = self._rms(x, L["ln_post"], cfg.rms_eps)
        g = h2 @ L["gate"].T
        return x + (g * jax.nn.sigmoid(g) * (h2 @ L["up"].T)) @ L["down"].T

    def _step_impl(self, w, caches, ids_t, pos):
        """ids_t (B,) int32, pos () int32 → (logits (B, V), caches)."""
        import jax.numpy as jnp
        from jax import lax

        cfg = self.cfg
        hd = cfg.head_dim
        b = ids_t.shape[0]
        pos = jnp.asarray(pos, jnp.int32)
        z = jnp.zeros((), jnp.int32)
        cos = lax.dynamic_slice(self._cos, (pos, z), (1, hd // 2))
        sin = lax.dynamic_slice(self._sin, (pos, z), (1, hd // 2))
        x = w["emb"][ids_t]                                     # (B, H)
        new_caches = []
        mask = (jnp.arange(self.max_len) <= pos)[None, :]       # (1, T)
        for L, (kc, vc) in zip(w["layers"], caches):

            def ctx_fn(h, L=L, kc=kc, vc=vc):
                q = (h @ L["q"].T).reshape(b, cfg.num_heads, 1, hd)
                k = (h @ L["k"].T).reshape(b, cfg.num_kv_heads, 1, hd)
                v = (h @ L["v"].T).reshape(b, cfg.num_kv_heads, 1, hd)
                q = _apply_rope(q, cos[None, None], sin[None, None])
                k = _apply_rope(k, cos[None, None], sin[None, None])
                kc2 = lax.dynamic_update_slice(kc, k, (z, z, pos, z))
                vc2 = lax.dynamic_update_slice(vc, v, (z, z, pos, z))
                new_caches.append((kc2, vc2))
                ctx = self._attend(q, kc2, vc2, mask)
                return ctx.reshape(b, cfg.num_heads * hd) @ L["o"].T

            x = self._layer(L, x, ctx_fn)
        x = self._rms(x, w["norm"], cfg.rms_eps)
        return x @ w["head"].T, new_caches

    def _step_slots_impl(self, w, caches, ids_t, pos):
        """Per-slot decode step for continuous-batching serving:
        ids_t (S,) int32, pos (S,) int32 → (logits (S, V), caches).

        Unlike ``_step_impl`` (one shared scalar position — a
        homogeneous batch decoded in lockstep), every cache slot here
        carries its OWN position: RoPE tables are gathered per slot,
        each slot's K/V row is written at its own ``pos`` (vmapped
        dynamic_update_slice), and the causal mask is per-slot
        (``t <= pos[s]``).  That is the core of continuous batching —
        requests admitted at different times decode in one program.
        Vacant slots run with pos=0/ids=0: their garbage K/V write lands
        in their own slot row only and admission's prefill scatter
        replaces the whole slot cache, so they never perturb live
        slots."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        cfg = self.cfg
        hd = cfg.head_dim
        s = ids_t.shape[0]
        pos = jnp.asarray(pos, jnp.int32)
        cos = self._cos[pos][:, None, None, :]      # (S,1,1,hd/2)
        sin = self._sin[pos][:, None, None, :]
        x = w["emb"][ids_t]                         # (S, H)
        new_caches = []
        mask = (jnp.arange(self.max_len)[None, :]
                <= pos[:, None])[:, None, None, :]  # (S,1,1,T)
        z = jnp.zeros((), jnp.int32)
        upd = jax.vmap(
            lambda c, u, p: lax.dynamic_update_slice(c, u, (z, p, z)))
        for L, (kc, vc) in zip(w["layers"], caches):

            def ctx_fn(h, L=L, kc=kc, vc=vc):
                q = (h @ L["q"].T).reshape(s, cfg.num_heads, 1, hd)
                k = (h @ L["k"].T).reshape(s, cfg.num_kv_heads, 1, hd)
                v = (h @ L["v"].T).reshape(s, cfg.num_kv_heads, 1, hd)
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
                kc2 = upd(kc, k, pos)
                vc2 = upd(vc, v, pos)
                new_caches.append((kc2, vc2))
                ctx = self._attend(q, kc2, vc2, mask)
                return ctx.reshape(s, cfg.num_heads * hd) @ L["o"].T

            x = self._layer(L, x, ctx_fn)
        x = self._rms(x, w["norm"], cfg.rms_eps)
        return x @ w["head"].T, new_caches

    def _prefill_rows_impl(self, w, ids, t0):
        """Batched full-sequence prompt pass over PADDED ids (B, Lp)
        returning each layer's raw post-RoPE K/V rows ``(B, Hkv, Lp,
        hd)`` — no max_len cache allocation, so the CALLER picks the
        storage layout: the offline path pads rows into per-batch
        max_len caches (:meth:`_prefill_impl`), the paged serving
        engine scatters them into pool blocks (the prefill→decode KV
        handoff).  Logits are gathered at each row's true last position
        (scalar or per-row vector ``t0``)."""
        import jax.numpy as jnp

        cfg = self.cfg
        hd = cfg.head_dim
        b, lp = ids.shape
        cos, sin = self._cos[:lp], self._sin[:lp]
        x = w["emb"][ids]                                   # (B, Lp, H)
        causal = jnp.tril(jnp.ones((lp, lp), bool))         # (Q, T)
        rows = []
        for L in w["layers"]:

            def ctx_fn(h, L=L):
                q = (h @ L["q"].T).reshape(b, lp, cfg.num_heads, hd) \
                    .transpose(0, 2, 1, 3)
                k = (h @ L["k"].T).reshape(b, lp, cfg.num_kv_heads, hd) \
                    .transpose(0, 2, 1, 3)
                v = (h @ L["v"].T).reshape(b, lp, cfg.num_kv_heads, hd) \
                    .transpose(0, 2, 1, 3)
                q = _apply_rope(q, cos[None, None], sin[None, None])
                k = _apply_rope(k, cos[None, None], sin[None, None])
                rows.append((k, v))
                ctx = self._attend(q, k, v, causal)
                return ctx.transpose(0, 2, 1, 3) \
                    .reshape(b, lp, cfg.num_heads * hd) @ L["o"].T

            x = self._layer(L, x, ctx_fn)
        t0v = jnp.asarray(t0, jnp.int32)
        if t0v.ndim == 0:
            x_last = jnp.take(x, t0v - 1, axis=1)
        else:
            # per-row true lengths (B,): serving admits prompts of
            # different lengths in one padded prefill, each row gathers
            # its own last real position
            x_last = jnp.take_along_axis(
                x, (t0v - 1)[:, None, None], axis=1)[:, 0]
        x_last = self._rms(x_last, w["norm"], cfg.rms_eps)
        return rows, x_last @ w["head"].T

    def _prefill_impl(self, w, ids, t0):
        """Prompt pass + full-length caches: K/V rows land at [0:Lp] of
        fresh (B, Hkv, max_len, hd) caches (pad rows are overwritten by
        decode steps starting at ``t0``, and the causal mask keeps them
        invisible to real rows).  One MXU-friendly forward instead of
        T0 serialized vector steps, compiled once per padded shape."""
        import jax.numpy as jnp
        from jax import lax

        cfg = self.cfg
        b = ids.shape[0]
        rows, logits = self._prefill_rows_impl(w, ids, t0)
        z = jnp.zeros((), jnp.int32)
        shape = (b, cfg.num_kv_heads, self.max_len, cfg.head_dim)
        caches = [
            (lax.dynamic_update_slice(jnp.zeros(shape, k.dtype), k,
                                      (z, z, z, z)),
             lax.dynamic_update_slice(jnp.zeros(shape, v.dtype), v,
                                      (z, z, z, z)))
            for k, v in rows]
        return caches, logits

    def _step_blocks_impl(self, w, pools, tables, ids_t, pos):
        """Per-slot decode step against a PAGED KV pool: same vector-
        position continuous-batching contract as
        :meth:`_step_slots_impl`, but K/V storage is block-granular.
        ``pools[l]`` is ``(kp, vp)`` each ``(num_blocks, Hkv,
        block_size, hd)`` shared by every slot; ``tables`` (S, MB)
        int32 holds each slot's block ids in logical order, vacant
        entries = ``num_blocks``.  The step scatters each slot's new
        K/V at ``(tables[s, pos//bs], pos%bs)`` — the sentinel id is
        out of bounds, so vacant slots' writes DROP — and gathers each
        slot's logical view ``(S, Hkv, MB*bs, hd)`` through a clamped
        table; garbage read through clamped sentinel entries sits at
        positions the causal mask (``t <= pos``) never exposes.  MB is
        static, so the compute cost matches the slot-ledger step while
        HBM capacity is the POOL size — bounded by tokens in flight,
        not max_len × slots."""
        import jax.numpy as jnp

        cfg = self.cfg
        hd = cfg.head_dim
        s = ids_t.shape[0]
        nb, hkv, bs, _ = pools[0][0].shape
        mb = tables.shape[1]
        t = mb * bs
        pos = jnp.asarray(pos, jnp.int32)
        cos = self._cos[pos][:, None, None, :]      # (S,1,1,hd/2)
        sin = self._sin[pos][:, None, None, :]
        x = w["emb"][ids_t]                         # (S, H)
        mask = (jnp.arange(t)[None, :]
                <= pos[:, None])[:, None, None, :]  # (S,1,1,T)
        blk = jnp.take_along_axis(tables, (pos // bs)[:, None],
                                  axis=1)[:, 0]     # (S,) physical block
        off = pos % bs
        gat = jnp.minimum(tables, nb - 1)           # clamp the sentinel
        new_pools = []
        for L, (kp, vp) in zip(w["layers"], pools):

            def ctx_fn(h, L=L, kp=kp, vp=vp):
                q = (h @ L["q"].T).reshape(s, cfg.num_heads, 1, hd)
                k = (h @ L["k"].T).reshape(s, cfg.num_kv_heads, 1, hd)
                v = (h @ L["v"].T).reshape(s, cfg.num_kv_heads, 1, hd)
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
                kp2 = kp.at[blk, :, off].set(k[:, :, 0, :], mode="drop")
                vp2 = vp.at[blk, :, off].set(v[:, :, 0, :], mode="drop")
                new_pools.append((kp2, vp2))
                kc = kp2[gat].transpose(0, 2, 1, 3, 4) \
                    .reshape(s, hkv, t, hd)
                vc = vp2[gat].transpose(0, 2, 1, 3, 4) \
                    .reshape(s, hkv, t, hd)
                ctx = self._attend(q, kc, vc, mask)
                return ctx.reshape(s, cfg.num_heads * hd) @ L["o"].T

            x = self._layer(L, x, ctx_fn)
        x = self._rms(x, w["norm"], cfg.rms_eps)
        return x @ w["head"].T, new_pools

    def _verify_blocks_impl(self, w, pools, tables, toks, pos0):
        """Speculative VERIFY forward against the paged pool: a widened
        :meth:`_step_blocks_impl` that advances every slot K = k+1
        candidate positions in ONE dispatch.  ``toks`` (S, K) int32 is
        ``[last_committed, draft_1 .. draft_k]`` per slot; ``pos0``
        (S,) is each slot's committed write cursor, so window column j
        carries absolute position ``pos0[s] + j``.  Returns greedy
        argmax over the (S, K, V) logits — column j is the target
        model's next-token choice AFTER consuming ``toks[s, :j+1]``,
        exactly what the acceptance rule compares drafts against.

        K/V for all K window tokens scatter into the slots' own blocks
        at their absolute positions (``mode="drop"`` on the sentinel
        id, and ids past ``max_len`` are forced to the sentinel, so
        vacant slots and over-budget columns write nothing).  Rejected
        columns need no cleanup: their rows sit beyond the rolled-back
        cursor where the causal mask (``t <= pos``) never exposes them,
        and the next verify window overwrites them in place — the
        stale-row invariant, now doing rollback duty.  The causal mask
        here is per-COLUMN (``t <= pos0[s] + j``), so draft_j attends
        the in-window K/V of draft_1..j-1 it was conditioned on."""
        import jax.numpy as jnp

        cfg = self.cfg
        hd = cfg.head_dim
        s, kk = toks.shape
        nb, hkv, bs, _ = pools[0][0].shape
        mb = tables.shape[1]
        t = mb * bs
        pos0 = jnp.asarray(pos0, jnp.int32)
        pw = pos0[:, None] + jnp.arange(kk, dtype=jnp.int32)[None, :]
        cos = self._cos[pw][:, None]                # (S,1,K,hd/2)
        sin = self._sin[pw][:, None]
        x = w["emb"][toks]                          # (S, K, H)
        mask = (jnp.arange(t)[None, None, :]
                <= pw[:, :, None])[:, None]         # (S,1,K,T)
        blk = jnp.take_along_axis(tables,
                                  jnp.minimum(pw // bs, mb - 1), axis=1)
        # columns past max_len have no legal row: force the sentinel so
        # the scatter drops instead of wrapping into a clamped block
        blk = jnp.where(pw < jnp.int32(self.max_len), blk, nb)  # (S,K)
        off = pw % bs
        gat = jnp.minimum(tables, nb - 1)
        new_pools = []
        for L, (kp, vp) in zip(w["layers"], pools):

            def ctx_fn(h, L=L, kp=kp, vp=vp):
                q = (h @ L["q"].T).reshape(s, kk, cfg.num_heads, hd) \
                    .transpose(0, 2, 1, 3)
                k = (h @ L["k"].T).reshape(s, kk, cfg.num_kv_heads, hd) \
                    .transpose(0, 2, 1, 3)
                v = (h @ L["v"].T).reshape(s, kk, cfg.num_kv_heads, hd) \
                    .transpose(0, 2, 1, 3)
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
                # scatter indices (S,K) pair with update (S,K,Hkv,hd)
                kp2 = kp.at[blk, :, off].set(
                    k.transpose(0, 2, 1, 3), mode="drop")
                vp2 = vp.at[blk, :, off].set(
                    v.transpose(0, 2, 1, 3), mode="drop")
                new_pools.append((kp2, vp2))
                kc = kp2[gat].transpose(0, 2, 1, 3, 4) \
                    .reshape(s, hkv, t, hd)
                vc = vp2[gat].transpose(0, 2, 1, 3, 4) \
                    .reshape(s, hkv, t, hd)
                ctx = self._attend(q, kc, vc, mask)     # (S,H,K,hd)
                return ctx.transpose(0, 2, 1, 3) \
                    .reshape(s, kk, cfg.num_heads * hd) @ L["o"].T

            x = self._layer(L, x, ctx_fn)
        x = self._rms(x, w["norm"], cfg.rms_eps)
        return x @ w["head"].T, new_pools               # (S, K, V)

    def _prefill_suffix_impl(self, w, prefix_kv, ids, t0, s0):
        """Prompt-SUFFIX prefill attending a reused prefix: the radix
        prefix cache supplies each row's leading ``s0[b]`` tokens of
        K/V (``prefix_kv[l] = (K, V)`` each (B, Hkv, Lpre, hd), dense
        copies gathered from shared pool blocks, sentinel-padded past
        ``s0[b]``), and only the novel suffix ``ids`` (B, Ls) runs
        through the transformer.  Suffix row j sits at absolute
        position ``s0[b] + j`` (RoPE + mask), attends every real prefix
        column (``t < s0[b]``) plus the suffix causally — bit-identical
        attention to a full prefill, at suffix-sized projection/MLP
        cost.  Returns the suffix rows' post-RoPE K/V (for the pool
        scatter into the request's PRIVATE blocks) and logits at each
        row's true last suffix position ``t0[b] - 1``.  Rows with no
        cache hit run with ``s0[b] = 0``: every prefix column masked,
        plain prefill semantics."""
        import jax.numpy as jnp

        cfg = self.cfg
        hd = cfg.head_dim
        b, ls = ids.shape
        lpre = prefix_kv[0][0].shape[2]
        s0 = jnp.asarray(s0, jnp.int32)
        pw = s0[:, None] + jnp.arange(ls, dtype=jnp.int32)[None, :]
        pw = jnp.minimum(pw, jnp.int32(self.max_len - 1))
        cos = self._cos[pw][:, None]                # (B,1,Ls,hd/2)
        sin = self._sin[pw][:, None]
        x = w["emb"][ids]                           # (B, Ls, H)
        mask_pre = (jnp.arange(lpre)[None, None, None, :]
                    < s0[:, None, None, None])      # (B,1,1,Lpre)
        mask_pre = jnp.broadcast_to(mask_pre, (b, 1, ls, lpre))
        mask_suf = jnp.broadcast_to(
            jnp.tril(jnp.ones((ls, ls), bool))[None, None],
            (b, 1, ls, ls))
        mask = jnp.concatenate([mask_pre, mask_suf], axis=-1)
        rows = []
        for L, (pk, pv) in zip(w["layers"], prefix_kv):

            def ctx_fn(h, L=L, pk=pk, pv=pv):
                q = (h @ L["q"].T).reshape(b, ls, cfg.num_heads, hd) \
                    .transpose(0, 2, 1, 3)
                k = (h @ L["k"].T).reshape(b, ls, cfg.num_kv_heads, hd) \
                    .transpose(0, 2, 1, 3)
                v = (h @ L["v"].T).reshape(b, ls, cfg.num_kv_heads, hd) \
                    .transpose(0, 2, 1, 3)
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
                rows.append((k, v))
                kc = jnp.concatenate([pk, k], axis=2)
                vc = jnp.concatenate([pv, v], axis=2)
                ctx = self._attend(q, kc, vc, mask)
                return ctx.transpose(0, 2, 1, 3) \
                    .reshape(b, ls, cfg.num_heads * hd) @ L["o"].T

            x = self._layer(L, x, ctx_fn)
        t0v = jnp.asarray(t0, jnp.int32)
        x_last = jnp.take_along_axis(
            x, (t0v - 1)[:, None, None], axis=1)[:, 0]
        x_last = self._rms(x_last, w["norm"], cfg.rms_eps)
        return rows, x_last @ w["head"].T

    def logits_at(self, ids):
        """Teacher-forced per-step decode over ``ids`` (B, T) returning
        logits at every position (B, T, V) — the parity-test surface for
        the single-token step path."""
        import jax.numpy as jnp
        import numpy as np

        ids = jnp.asarray(ids, jnp.int32)
        b, t = ids.shape
        w = self._weights()
        caches = self.init_cache(b)
        outs = []
        for p in range(t):
            logits, caches = self._step(w, caches, ids[:, p], jnp.int32(p))
            outs.append(np.asarray(logits))
        return np.stack(outs, axis=1)

    def _pick(self, logits, key, temperature, top_p, top_k, do_sample,
              use_top_p):
        """Greedy or filtered sampling from last-position logits (B, V).
        ``top_k``/``do_sample``/``use_top_p`` are trace-static;
        temperature/top_p ride as traced scalars so tuning them doesn't
        recompile.  The nucleus filter (two full-vocab sorts per token)
        only compiles in when actually requested."""
        import jax
        import jax.numpy as jnp

        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lg = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
        if top_k and top_k < lg.shape[-1]:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        if use_top_p:
            # nucleus: drop tokens whose EXCLUSIVE cumulative prob ≥
            # top_p (the top token always survives)
            srt = jnp.sort(lg, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1) - probs
            count = jnp.maximum((cum < top_p).sum(-1), 1)
            thresh = jnp.take_along_axis(srt, (count - 1)[:, None], axis=1)
            lg = jnp.where(lg < thresh, -jnp.inf, lg)
        return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)

    def _generate_impl(self, w, ids, t0, key, temperature, top_p,
                       n_steps, top_k, do_sample, use_top_p):
        """Padded ids (B, Lp) + traced true length ``t0`` → (B, n_steps)
        continuation in one XLA program: batched prefill, then a decode
        scan (first new token comes from the prefill logits; decode
        steps overwrite the pad K/V rows starting at ``t0``)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        caches, logits = self._prefill_impl(w, ids, t0)
        key, sub = jax.random.split(key)
        cur = self._pick(logits, sub, temperature, top_p, top_k,
                         do_sample, use_top_p)

        def decode_body(carry, _):
            caches, cur, pos, key = carry
            logits, caches = self._step_impl(w, caches, cur, pos)
            key, sub = jax.random.split(key)
            nxt = self._pick(logits, sub, temperature, top_p, top_k,
                             do_sample, use_top_p)
            return (caches, nxt, pos + 1, key), nxt

        (_, _, _, _), toks = lax.scan(
            decode_body,
            (caches, cur, jnp.asarray(t0, jnp.int32), key), None,
            length=n_steps - 1)
        return jnp.concatenate([cur[:, None], toks.T], axis=1)

    @staticmethod
    def _bucket(n, quantum=16):
        b = quantum
        while b < n:
            b *= 2
        return b

    def generate(self, ids, max_new_tokens, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, seed=None):
        """Decode (greedy, or sampled with ``do_sample=True``).  Prompt
        length and step count are padded to power-of-two buckets (true
        length rides in as a traced scalar), so nearby calls reuse ONE
        compiled XLA program instead of retracing per exact
        (prompt_len, max_new_tokens)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        ids = np.asarray(ids, np.int32)
        b, t0 = ids.shape
        n = int(max_new_tokens)
        if n < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if t0 + n > self.max_len:
            raise MXNetError("max_len exceeded; build a larger decoder")
        lp = min(self._bucket(t0), self.max_len)
        nb = min(self._bucket(n), self.max_len - lp)
        if nb < n:  # bucketed padding doesn't fit: run exact shapes
            lp, nb = t0, n
        ids_pad = np.zeros((b, lp), np.int32)
        ids_pad[:, :t0] = ids
        if not do_sample:
            # greedy must not touch the global RNG stream (reproducible
            # training runs interleave greedy eval generates)
            key = jax.random.PRNGKey(0)
        elif seed is None:
            from .. import random as mx_random

            key = mx_random.next_key()
        else:
            key = jax.random.PRNGKey(int(seed))
        toks = self._gen(self._weights(), jnp.asarray(ids_pad),
                         jnp.int32(t0), key,
                         jnp.float32(temperature), jnp.float32(top_p),
                         int(nb), int(top_k), bool(do_sample),
                         bool(do_sample and top_p < 1.0))
        return np.concatenate([ids, np.asarray(toks)[:, :n]], axis=1)


def llama3_8b(**overrides):
    """Llama-3-8B architecture (BASELINE config 5)."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["llama3_8b"],
                                           **overrides}))


def llama_tiny(**overrides):
    """Tiny config for tests/dryruns."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["llama_tiny"],
                                           **overrides}))


def mixtral_8x7b(**overrides):
    """Mixtral-8x7B sparse-MoE architecture (beyond-reference model
    family: MoE + expert parallelism, SURVEY §2.3 D9)."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["mixtral_8x7b"],
                                           **overrides}))


def mixtral_tiny(**overrides):
    """Tiny MoE config for tests/dryruns."""
    return LlamaForCausalLM(LlamaConfig(**{**LLAMA_CONFIGS["mixtral_tiny"],
                                           **overrides}))


def _lm_head(net, h):
    """Project hidden states to vocab logits for ``net`` — THE single
    definition of the head routing: tied configs reuse the embedding
    matrix ((V, H), recorded ``tied_lm_head`` op so the head gradient
    accumulates into the tied embedding), untied use the dedicated
    Dense.  Every forward path (plain, GPipe) must call this so the
    routing can't diverge (ADVICE r3: the pipelined forward once used
    the dead lm_head for tied configs); the fused 1F1B loss keeps an
    inline jnp equivalent pinned by the tied/untied grad-equality
    tests."""
    if net._cfg.tie_embeddings:
        from ..ops.registry import apply_op

        w = net.model.embed_tokens.weight.data()
        return apply_op(lambda hr, wr: hr @ wr.T, h, w,
                        name="tied_lm_head")
    return net.lm_head(h)


def packed_lm_loss(logits, labels, loss_mask):
    """Mean next-token cross-entropy over a packed batch, masked to the
    real targets (``data.PackedBatch``: padding and each document's
    last position carry ``loss_mask`` 0 — no cross-document
    prediction).  f32 log-softmax accumulation like
    ``softmax_cross_entropy``; that op sums over the whole batch, which
    can't express a per-token mask — hence this dedicated raw op.
    logits (B, T, V), labels (B, T) int, loss_mask (B, T) float."""
    from ..ops.registry import apply_op

    def f(lg, lb, m):
        import jax
        import jax.numpy as jnp

        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(
            lp, lb[..., None].astype(jnp.int32), axis=-1)[..., 0]
        mf = m.astype(jnp.float32)
        return -(ll * mf).sum() / jnp.maximum(mf.sum(), 1.0)

    return apply_op(f, logits, labels, loss_mask, name="packed_lm_loss")


def llama_pipeline_forward(net, input_ids, n_microbatches, mesh=None,
                           axis_name="pp"):
    """Forward the SAME ``LlamaForCausalLM`` Block over a GPipe pipeline
    (``parallel.pipeline_apply``, SURVEY §2.3 D7 — new capability).

    The decoder stack is cut into ``mesh[axis_name]`` equal stages; each
    stage applies its layers with the ORIGINAL Block code (layer 0 is the
    template whose parameter handles are swapped per layer inside the
    staged function), activations hop stage→stage over the ICI ring, and
    embedding/final-norm/LM-head run outside the pipeline, replicated.
    The per-layer parameter stacking is recorded nd ops, so
    ``backward()`` routes pipeline gradients into every layer's own
    ``Parameter.grad()`` and ``gluon.Trainer`` works unchanged —
    equivalence with the unpipelined forward (loss AND per-param grads)
    is asserted in tests/test_ring.py.

    ``input_ids``: (B, T) with ``B % n_microbatches == 0``; returns
    logits (B, T, vocab).
    """
    from .. import parallel
    from ..ndarray import NDArray
    from ..ops import tensor as tops

    mesh = mesh or parallel.current_mesh()
    if mesh is None:
        raise MXNetError("no active mesh; call parallel.set_mesh first")
    n_stages = mesh.shape[axis_name]
    batch = input_ids.shape[0]
    if batch % n_microbatches:
        raise MXNetError(
            f"batch {batch} not divisible by {n_microbatches} "
            "microbatches")

    h = net.model.embed_tokens(input_ids)  # (B, T, H)
    t_len, hidden = h.shape[1], h.shape[2]
    mbs = h.reshape((n_microbatches, batch // n_microbatches, t_len,
                     hidden))

    mach = _pipeline_machinery(net, n_stages)
    names, shells, lps = mach["names"], mach["shells"], mach["lps"]
    stacked = _stacked_layer_params(net, names, n_stages, lps)
    saved = [sh._data for sh in shells]

    try:
        out = parallel.pipeline_apply(mach["stage_fn"], stacked, mbs,
                                      mesh=mesh, axis_name=axis_name)
    finally:
        for sh, s in zip(shells, saved):
            sh._data = s
    h_out = out.reshape((batch, t_len, hidden))
    h_out = net.model.norm(h_out)
    return _lm_head(net, h_out)


def _apply_layers_scanned(model, h, segment_ids=None):
    """cfg.scan_layers: apply the decoder stack as
    ``lax.scan(checkpoint_wrap(layer, tier))`` over a stacked parameter
    tree, the tier resolved by the memory policy (default "layer").

    The layer-0 Block is the compile template (handle-swap per
    iteration, the pipeline machinery's trick), so the stack traces and
    compiles ONE layer regardless of depth, XLA allocates one layer's
    buffers instead of L copies, and each iteration rematerializes in
    the backward (r4 finding: a python layer loop cost ~1 GiB x L of
    XLA temp that scan removes by construction —
    tools/scale_proof.py).  The per-layer parameters are restacked with
    RECORDED ops every call, so gradients reach each layer's own
    Parameter and ``gluon.Trainer`` works unchanged."""
    from ..ops import tensor as tops
    from ..ops.registry import apply_op

    mach = _scan_machinery(model, _resolve_model_remat(model, h),
                           with_seg=segment_ids is not None)
    names, shells = mach["names"], mach["shells"]
    per_layer = [ly._collect_params_with_prefix()
                 for ly in model.layers]
    stacked = [tops.stack(*[lp[n].data() for lp in per_layer], axis=0)
               for n in names]
    saved = [sh._data for sh in shells]
    try:
        if segment_ids is not None:
            res = apply_op(mach["fn"], h, segment_ids, *stacked,
                           name="scan_layers_packed")
        else:
            res = apply_op(mach["fn"], h, *stacked, name="scan_layers")
        # static build-time bool out of the machinery cache (keyed on
        # the numerics mode), not a tracer
        if not mach["numerics"]:  # mxlint: allow=T2
            return res
        # unpack the stacked per-layer stat ys (unused downstream, so
        # autograd feeds them zero cotangents) and queue them for the
        # stride harvest under decoder.<i> paths
        out, l2, maxabs, mean, nan, inf = res
        _numerics.tap_stacked("decoder", {
            "l2": l2._data, "maxabs": maxabs._data, "mean": mean._data,
            "nan": nan._data, "inf": inf._data})
        return out
    finally:
        for sh, s in zip(shells, saved):
            sh._data = s


def _layer_template(layers):
    """(template layer-0 Block, sorted param names, shell handles) — the
    ONE extraction of the handle-swap machinery's raw ingredients,
    shared by the scan forward and the pipeline machinery (the 1F1B
    commit unified the GPipe/1F1B copies; this keeps scan on the same
    helper instead of growing a third)."""
    template = layers[0]
    tparams = template._collect_params_with_prefix()
    names = sorted(tparams)
    shells = [tparams[n]._data for n in names]
    return template, names, shells


def _resolve_model_remat(model, h):
    """The decoder stack's remat tier: ``set_remat()``'s choice, the
    planner's pick for "auto" (cheapest tier that fits, sized at the
    live activation shape), or the historical "layer" default."""
    from ..memory import policy as _mem_policy

    tier = _mem_policy.normalize(getattr(model, "_remat", "layer"))
    if tier != "auto":
        if tier != "none":
            _mem_policy.record_policy(tier, "forced")
        return tier
    import numpy as np

    from .. import parallel

    batch_b = int(np.prod(h.shape)) * np.dtype(h.dtype).itemsize
    tier, _plan = _mem_policy.auto_tier(
        model, mesh=parallel.current_mesh(), batch_bytes=batch_b)
    return tier


def _scan_machinery(model, remat="layer", with_seg=False):
    """Cached per-(model, remat-tier, packed?) scan plumbing
    (identity-stable like :func:`_pipeline_machinery`, so jit caches
    hit across steps; a tier change — or switching between packed and
    plain batches — rebuilds)."""
    cache = getattr(model, "_scan_mach", None)
    numerics_on = _numerics.trace_enabled()
    # remat is a host-side tier string, never a tracer
    if (cache is not None and cache["remat"] == remat  # mxlint: allow=T2
            and cache["with_seg"] == with_seg
            and cache["numerics"] == numerics_on):
        return cache
    from ..gluon.block import _trace_guard
    from ..memory.policy import checkpoint_wrap
    from ..ndarray import NDArray

    template, names, shells = _layer_template(list(model.layers))

    if with_seg:
        # packed path: segment ids are a scan-invariant second input to
        # every layer (same (B, T) array each iteration — lax.scan
        # closes over it, only the stacked params are scanned)
        def apply_one(sl, carry, segr):
            for sh, s in zip(shells, sl):
                sh._data = s
            with _trace_guard():  # inline the template (no nested jit)
                return template(NDArray(carry), NDArray(segr))._data
    else:
        def apply_one(sl, carry):
            for sh, s in zip(shells, sl):
                sh._data = s
            with _trace_guard():  # inline the template (no nested jit)
                return template(NDArray(carry))._data

    import jax

    wrapped = checkpoint_wrap(apply_one, remat)

    # numerics: per-layer output stats ride the scan as stacked ys —
    # computed inside the same compile, stacked (L,) per stat by
    # lax.scan itself, and returned flat (apply_op dispatches tuples of
    # arrays).  Taps inside the body would hand scan tracers to the
    # collector; the ys are the only legal exit.
    def _body_ys(new):
        if not numerics_on:
            return ()
        st = _numerics.stats_of(new)
        return (st["l2"], st["maxabs"], st["mean"], st["nan"], st["inf"])

    if with_seg:
        def _scan_raw(hr, segr, *stk):
            from jax import lax

            def body(carry, sl):
                new = wrapped(sl, carry, segr)
                return new, _body_ys(new)

            out, ys = lax.scan(body, hr, tuple(stk))
            return (out,) + ys if numerics_on else out
    else:
        def _scan_raw(hr, *stk):
            from jax import lax

            def body(carry, sl):
                new = wrapped(sl, carry)
                return new, _body_ys(new)

            out, ys = lax.scan(body, hr, tuple(stk))
            return (out,) + ys if numerics_on else out

    # jit the scan program: (a) eager steps run ONE compiled program
    # instead of a traced-eager loop, and (b) shard_map-based layers
    # (ring/Ulysses attention) require a jit around them — eager scan
    # evaluation of a shard_map body is NotImplemented in jax
    fn = jax.jit(_scan_raw)

    cache = {"names": names, "shells": shells, "fn": fn,
             "apply_one": apply_one, "remat": remat,
             "with_seg": with_seg, "numerics": numerics_on}
    model._scan_mach = cache
    return cache


def _pipeline_machinery(net, n_stages):
    """Cached per-(net, n_stages) pipeline plumbing: template layer,
    its parameter shells (handle-swap targets), and the stage function.
    Caching keeps ``stage_fn`` IDENTITY stable across training steps so
    :func:`parallel.pipeline_train_1f1b`'s program cache hits instead of
    re-tracing the whole schedule every call.  Shared by the GPipe
    forward and the fused 1F1B train step."""
    from ..ndarray import NDArray

    cache = getattr(net, "_pp_machinery", None)
    if cache is not None and cache["n_stages"] == n_stages:
        return cache
    layers = list(net.model.layers)
    n_layers = len(layers)
    if n_layers % n_stages:
        raise MXNetError(
            f"{n_layers} decoder layers not divisible into "
            f"{n_stages} pipeline stages")
    lps = n_layers // n_stages
    template, names, shells = _layer_template(layers)

    def stage_fn(ptree, x_raw):
        out = x_raw
        for i in range(lps):
            for sh, name in zip(shells, names):
                sh._data = ptree[name][i]
            out = template(NDArray(out))._data
        return out

    cache = {"n_stages": n_stages, "names": names, "shells": shells,
             "template": template, "lps": lps, "stage_fn": stage_fn,
             "loss_fn": None}
    net._pp_machinery = cache
    return cache


def _stacked_layer_params(net, names, n_stages, lps):
    """{name: (S, L/S, *shape)} stacks of the per-layer parameters via
    RECORDED nd ops, so gradients through the stack reach each layer's
    own Parameter.  Rebuilt every call (the values change each step);
    the trace-stable machinery lives in :func:`_pipeline_machinery`."""
    from ..ops import tensor as tops

    per_layer_params = [ly._collect_params_with_prefix()
                        for ly in net.model.layers]
    stacked = {}
    for name in names:
        flat = tops.stack(*[lp[name].data() for lp in per_layer_params],
                          axis=0)
        stacked[name] = flat.reshape(
            (n_stages, lps) + tuple(flat.shape[1:]))
    return stacked


class _FusedGradStep(autograd.Function):
    """Wire a fused train step (loss + precomputed grads, e.g. the 1F1B
    schedule) into the tape: forward runs the runner, backward returns
    the stashed gradients scaled by the incoming cotangent."""

    def __init__(self, runner):
        super().__init__()
        self._runner = runner

    def forward(self, *inputs):
        loss, grads = self._runner(*inputs)
        self._grads = grads
        return loss

    def backward(self, dloss):
        from ..ndarray import NDArray

        scale = dloss._data
        return tuple(
            None if g is None else NDArray(g._data * scale)
            for g in self._grads)


def llama_pipeline_train_step(net, input_ids, labels, n_microbatches,
                              mesh=None, axis_name="pp"):
    """Fused 1F1B pipeline train step for a ``LlamaForCausalLM``: one
    compiled program interleaves each microbatch's backward right behind
    its forward (``parallel.pipeline_train_1f1b`` — peak activation
    memory O(S) instead of GPipe's O(M)), with the final RMSNorm + LM
    head + token cross-entropy computed on the last stage and the
    embedding stack outside the schedule.  Returns the MEAN token loss
    as a recorded NDArray: ``loss.backward()`` deposits gradients into
    every parameter (decoder layers via the stacked-params path,
    embedding via the schedule's input cotangent, norm/head via tail
    grads), so ``gluon.Trainer`` works unchanged."""
    import jax
    import jax.numpy as jnp

    from .. import parallel
    from ..ndarray import NDArray

    mesh = mesh or parallel.current_mesh()
    if mesh is None:
        raise MXNetError("no active mesh; call parallel.set_mesh first")
    n_stages = mesh.shape[axis_name]
    batch = input_ids.shape[0]
    if batch % n_microbatches:
        raise MXNetError(
            f"batch {batch} not divisible by {n_microbatches} "
            "microbatches")
    cfg = net._cfg
    eps = float(cfg.rms_eps)

    h = net.model.embed_tokens(input_ids)  # recorded
    t_len, hidden = h.shape[1], h.shape[2]
    mbs = h.reshape((n_microbatches, batch // n_microbatches, t_len,
                     hidden))
    lab_mbs = labels.reshape((n_microbatches,
                              batch // n_microbatches, t_len))
    mach = _pipeline_machinery(net, n_stages)
    names, shells, lps = mach["names"], mach["shells"], mach["lps"]
    stacked = _stacked_layer_params(net, names, n_stages, lps)
    saved = [sh._data for sh in shells]
    norm_w = net.model.norm.weight.data()
    # tied models reuse the embedding matrix as the LM head (same (V, H)
    # layout as lm_head.weight) — the tape then accumulates BOTH the
    # input-cotangent and the head contributions into the embedding
    head_w = (net.model.embed_tokens.weight.data()
              if cfg.tie_embeddings else net.lm_head.weight.data())

    if mach["loss_fn"] is None:
        def loss_fn(out, lab, tail):
            nw, hw = tail
            xf = out.astype(jnp.float32)
            var = (xf * xf).mean(axis=-1, keepdims=True)
            hn = (xf * jax.lax.rsqrt(var + eps)
                  * nw.astype(jnp.float32)).astype(out.dtype)
            logits = hn @ hw.T
            ls = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(
                ls, lab.astype(jnp.int32)[..., None], axis=-1)
            return jnp.sum(nll)

        mach["loss_fn"] = loss_fn
    stack_leaves = [stacked[name] for name in names]

    def runner(mbs_nd, lab_nd, *leaf_nds):
        k = len(names)
        stack_tree = {name: leaf_nds[i]
                      for i, name in enumerate(names)}
        tail = tuple(leaf_nds[k:])
        try:
            loss, grads, tgrads, dxs = parallel.pipeline_train_1f1b(
                mach["stage_fn"], mach["loss_fn"], stack_tree, mbs_nd,
                lab_nd, tail_params=tail, mesh=mesh,
                axis_name=axis_name)
        finally:
            for sh, s_ in zip(shells, saved):
                sh._data = s_
        return loss, (dxs, None,
                      *[grads[name] for name in names],
                      *list(jax.tree_util.tree_leaves(tgrads)))

    loss_sum = _FusedGradStep(runner)(mbs, lab_mbs, *stack_leaves,
                                      norm_w, head_w)
    return loss_sum / float(batch * t_len)


def llama_param_pspecs(net, mesh, tp_axis="tp", ep_axis="ep"):
    """{param_name (structural): partition-spec tuple} for the megatron
    TP/EP layout over ``mesh`` — used by :func:`shard_llama` (placement
    of real arrays) AND by the abstract 8B lowering proof
    (ShapeDtypeStruct shardings with no memory).  Params not listed are
    replicated (spec ``()``).

    The rules themselves live in the partition engine
    (``parallel.partition.MIXTRAL_RULES`` — the llama table plus the
    MoE expert-bank rows, which match nothing on a dense net); this
    function just resolves them against the net's parameter paths and
    ``mesh``, renaming the canonical 'tp'/'ep' axes when asked."""
    from ..parallel import partition as _pt

    rename = {"tp": tp_axis, "ep": ep_axis}
    rules = _pt.PartitionRules(
        [(pat, tuple(rename.get(a, a) if isinstance(a, str) else a
                     for a in spec))
         for pat, spec in _pt.MIXTRAL_RULES])
    shapes = {name: p.shape
              for name, p in net._collect_params_with_prefix().items()}
    specs = rules.specs(shapes, mesh)
    if net._cfg.tie_embeddings:
        # the tied head reads the embedding matrix; its own (dead)
        # weight stays replicated exactly as the hand-rolled table did
        specs.pop("lm_head.weight", None)
    return specs


def shard_llama(net, mesh=None, tp_axis="tp", dp_axis="dp", ep_axis="ep"):
    """Annotate megatron-style TP shardings over ``mesh`` (pjit/GSPMD
    derives the collectives — SURVEY §2.3 D6, new capability):

    - q/k/v/gate/up: column-parallel (output dim split over tp)
    - o/down:       row-parallel (input dim split over tp)
    - embed/lm_head: vocab-parallel
    - MoE layers: expert bank sharded over ``ep`` (+tp within experts)
    Replicates everything else.  Weights are stored (out, in), so the
    output dim is axis 0.  The rules live in
    :func:`llama_param_pspecs`; this function applies them to the
    initialized arrays.
    """
    from .. import parallel

    mesh = mesh or parallel.current_mesh()
    has_tp = mesh is not None and tp_axis in mesh.shape
    has_ep = mesh is not None and ep_axis in mesh.shape
    if mesh is None or not (has_tp or has_ep):
        parallel.replicate_block_params(net)
        return net
    parallel.replicate_block_params(net)  # baseline: replicate all
    params = net._collect_params_with_prefix()
    for name, spec in llama_param_pspecs(net, mesh, tp_axis=tp_axis,
                                         ep_axis=ep_axis).items():
        parallel.shard_param(params[name], spec, mesh)
    return net
