"""Image decode + augmentation.

Reference: ``python/mxnet/image/image.py:?`` (``imdecode``/``imresize``/
augmenter classes/``ImageIter``) over OpenCV; ``src/operator/image/`` for
the on-device resize/normalize ops.

TPU-native split: byte decode + geometric augmentation stay on host (cv2),
photometric normalize can run either host-side (numpy, prefetch thread) or
on device via the image ops in gluon transforms.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["imdecode", "imresize", "imread", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize", "ImageIter",
           "augment_basic", "augment_geom",
           "CreateAugmenter", "Augmenter", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "HorizontalFlipAug", "CastAug"]


def imdecode_raw(buf, flag=1):
    """bytes → HWC BGR→RGB uint8 array (host)."""
    import cv2

    img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
    if img is None:
        raise MXNetError("failed to decode image bytes")
    if img.ndim == 3 and img.shape[2] == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return img


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Reference ``mx.image.imdecode`` → NDArray HWC."""
    import cv2

    img = cv2.imdecode(np.frombuffer(
        buf if isinstance(buf, bytes) else bytes(buf), dtype=np.uint8), flag)
    if img is None:
        raise MXNetError("failed to decode image")
    if to_rgb and img.ndim == 3 and img.shape[2] == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return NDArray(img)


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    import cv2

    arr = src.asnumpy() if isinstance(src, NDArray) else src
    out = cv2.resize(arr, (w, h), interpolation=interp)
    return NDArray(out) if isinstance(src, NDArray) else out


def resize_short(src, size, interp=2):
    """Resize shorter edge to ``size`` (reference ``resize_short``)."""
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, size * h // w
    else:
        new_w, new_h = size * w // h, size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return NDArray(out) if isinstance(src, NDArray) else out


def center_crop(src, size, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(arr, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return (NDArray(out) if isinstance(src, NDArray) else out,
            (x0, y0, new_w, new_h))


def random_crop(src, size, interp=2, rng=None):
    rng = rng or np.random
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    new_w, new_h = size
    x0 = rng.randint(0, max(w - new_w, 0) + 1)
    y0 = rng.randint(0, max(h - new_h, 0) + 1)
    out = fixed_crop(arr, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return (NDArray(out) if isinstance(src, NDArray) else out,
            (x0, y0, new_w, new_h))


def color_normalize(src, mean, std=None):
    arr = src.asnumpy().astype(np.float32) if isinstance(src, NDArray) \
        else np.asarray(src, np.float32)
    arr = arr - np.asarray(mean, np.float32)
    if std is not None:
        arr = arr / np.asarray(std, np.float32)
    return NDArray(arr) if isinstance(src, NDArray) else arr


def augment_geom(img, data_shape, rng, rand_crop=False, rand_mirror=False,
                 resize=-1):
    """The GEOMETRIC half of the ImageRecordIter augmentation chain
    (resize-short → crop → mirror), kept host-side on uint8 where cv2 is
    cheap.  Returns HWC uint8; the numeric half (scale/mean/std/CHW)
    belongs on DEVICE so batches cross host→HBM as uint8 — 4× less
    transfer than float32 (see ImageRecordIter._device_finish)."""
    import cv2

    if resize > 0:
        img = resize_short(img, resize)
    c, h, w = data_shape
    if img.shape[0] != h or img.shape[1] != w:
        if rand_crop and img.shape[0] >= h and img.shape[1] >= w:
            img, _ = random_crop(img, (w, h), rng=rng)
        else:
            ih, iw = img.shape[:2]
            if ih < h or iw < w:
                img = cv2.resize(img, (max(w, iw), max(h, ih)))
            img, _ = center_crop(img, (w, h))
    if rand_mirror and rng.rand() < 0.5:
        img = img[:, ::-1]
    return img


def augment_basic(img, data_shape, rng, mean=(0, 0, 0), std=(1, 1, 1),
                  scale=1.0, rand_crop=False, rand_mirror=False, resize=-1):
    """The full ImageRecordIter augmentation chain (reference
    src/io/image_aug_default.cc:?): resize-short → crop → mirror →
    normalize → CHW.  Host-side numpy; ImageRecordIter uses
    ``augment_geom`` + a device-side numeric stage instead."""
    img = augment_geom(img, data_shape, rng, rand_crop=rand_crop,
                       rand_mirror=rand_mirror, resize=resize)
    img = img.astype(np.float32) * scale
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if mean.any():
        img = img - mean
    if (std != 1).any():
        img = img / std
    return np.transpose(img, (2, 0, 1))  # HWC → CHW


# --- augmenter classes (reference image.py Augmenter family) ----------------

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            out = arr[:, ::-1].copy()
            return NDArray(out) if isinstance(src, NDArray) else out
        return src


class CastAug(Augmenter):
    def __init__(self, typ=np.float32):
        super().__init__(typ=str(typ))
        self.typ = typ

    def __call__(self, src):
        if isinstance(src, NDArray):
            return src.astype(self.typ)
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference ``CreateAugmenter``)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    return auglist


class ImageIter:
    """Python image iterator over record files or file lists (reference
    ``mx.image.ImageIter``) — thin wrapper over io.ImageRecordIter."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 shuffle=False, aug_list=None, **kwargs):
        from .. import io as mxio

        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec in this build")
        self._inner = mxio.ImageRecordIter(
            path_imgrec=path_imgrec, data_shape=data_shape,
            batch_size=batch_size, shuffle=shuffle, **kwargs)

    def __iter__(self):
        return self

    def __next__(self):
        return self._inner.next()

    next = __next__

    def reset(self):
        self._inner.reset()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


# detection iterator + box-aware augmenters (reference image/detection.py)
from .detection import (ImageDetIter, CreateDetAugmenter,  # noqa: E402,F401
                        DetHorizontalFlipAug, DetResizeAug,
                        DetRandomCropAug, DetAugmenter)
