"""Detection image iterator + box-aware augmenters.

Reference: ``python/mxnet/image/detection.py:?`` (`ImageDetIter`,
``CreateDetAugmenter``) + C++ ``image_det_aug_default.cc:?`` (SURVEY §2.5)
— augmentations must transform the ground-truth boxes together with the
pixels (flip mirrors x-coords, crop shifts/clips boxes).

Label wire format (reference contract): per image
``[header_width, object_width, (extra...), obj0, obj1, ...]`` where each
object is ``[class, xmin, ymin, xmax, ymax]`` normalized to [0, 1].
``ImageDetIter.next`` emits padded (B, max_objs, 5) labels (-1 rows for
absent objects) — the shape ``MultiBoxTarget`` consumes.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter
from ..ndarray import NDArray
from . import imdecode_raw, imresize

__all__ = ["DetAugmenter", "DetHorizontalFlipAug", "DetResizeAug",
           "DetRandomCropAug", "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    def __call__(self, img, boxes, rng):
        raise NotImplementedError


class DetResizeAug(DetAugmenter):
    """Resize pixels; normalized boxes are scale-invariant."""

    def __init__(self, size):
        self.size = size if isinstance(size, (tuple, list)) else \
            (size, size)

    def __call__(self, img, boxes, rng):
        return imresize(img, self.size[0], self.size[1]), boxes


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror pixels AND x-coordinates with probability p."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, img, boxes, rng):
        if rng.uniform() < self.p:
            img = img[:, ::-1, :]
            if len(boxes):
                flipped = boxes.copy()
                flipped[:, 1] = 1.0 - boxes[:, 3]
                flipped[:, 3] = 1.0 - boxes[:, 1]
                boxes = flipped
        return img, boxes


class DetRandomCropAug(DetAugmenter):
    """Random crop (applied with probability ``p``) keeping boxes whose
    center survives (reference min_object_covered-style constraint,
    simplified)."""

    def __init__(self, min_crop=0.6, attempts=10, p=1.0):
        self.min_crop = max(min_crop, 0.1)  # never emit zero-size crops
        self.attempts = attempts
        self.p = p

    def __call__(self, img, boxes, rng):
        if rng.uniform() >= self.p:
            return img, boxes
        h, w = img.shape[:2]
        for _ in range(self.attempts):
            scale = rng.uniform(self.min_crop, 1.0)
            cw, ch = max(int(w * scale), 1), max(int(h * scale), 1)
            x0 = rng.randint(0, w - cw + 1)
            y0 = rng.randint(0, h - ch + 1)
            if not len(boxes):
                return img[y0:y0 + ch, x0:x0 + cw], boxes
            cx = (boxes[:, 1] + boxes[:, 3]) / 2 * w
            cy = (boxes[:, 2] + boxes[:, 4]) / 2 * h
            keep = ((cx >= x0) & (cx < x0 + cw) &
                    (cy >= y0) & (cy < y0 + ch))
            if not keep.any():
                continue
            nb = boxes[keep].copy()
            nb[:, 1] = np.clip((nb[:, 1] * w - x0) / cw, 0, 1)
            nb[:, 3] = np.clip((nb[:, 3] * w - x0) / cw, 0, 1)
            nb[:, 2] = np.clip((nb[:, 2] * h - y0) / ch, 0, 1)
            nb[:, 4] = np.clip((nb[:, 4] * h - y0) / ch, 0, 1)
            return img[y0:y0 + ch, x0:x0 + cw], nb
        return img, boxes


class DetNormalizeAug(DetAugmenter):
    """Per-channel mean/std pixel normalization (boxes untouched)."""

    def __init__(self, mean, std):
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def __call__(self, img, boxes, rng):
        img = np.asarray(img, np.float32)
        if self.mean is not None:
            img = img - self.mean
        if self.std is not None:
            img = img / self.std
        return img, boxes


class DetResizeShortAug(DetAugmenter):
    """Resize the short edge to ``size`` keeping aspect (boxes are
    normalized, so unchanged)."""

    def __init__(self, size):
        self.size = int(size)

    def __call__(self, img, boxes, rng):
        h, w = img.shape[:2]
        scale = self.size / min(h, w)
        return imresize(img, max(1, int(w * scale)),
                        max(1, int(h * scale))), boxes


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_mirror=False,
                       mean=None, std=None, **kwargs):
    """Reference ``CreateDetAugmenter``: standard detection pipeline.
    ``rand_crop`` is the PROBABILITY of applying the random crop
    (reference contract)."""
    augs = []
    if resize > 0:
        augs.append(DetResizeShortAug(resize))
    if rand_crop > 0:
        augs.append(DetRandomCropAug(min_crop=0.6, p=float(rand_crop)))
    augs.append(DetResizeAug((data_shape[2], data_shape[1])))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if mean is not None or std is not None:
        augs.append(DetNormalizeAug(mean, std))
    return augs


class ImageDetIter(DataIter):
    """Reference ``mx.image.ImageDetIter``: record-file (or in-memory)
    detection batches with box-aware augmentation."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imgidx=None, imglist=None, aug_list=None,
                 shuffle=False, mean=None, std=None, seed=0, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self._rng = np.random.RandomState(seed)
        self._aug = aug_list if aug_list is not None else \
            CreateDetAugmenter(self.data_shape)
        self._mean = np.asarray(mean, np.float32) if mean is not None \
            else None
        self._std = np.asarray(std, np.float32) if std is not None else None
        self._shuffle = shuffle
        self._records = []   # list of (imgbytes_or_array, boxes (N,5))
        if path_imgrec is not None:
            from .. import recordio

            rec = recordio.MXIndexedRecordIO(
                path_imgidx or path_imgrec.replace(".rec", ".idx"),
                path_imgrec, "r")
            for k in rec.keys:
                header, img = recordio.unpack(rec.read_idx(k))
                self._records.append((img, self._parse_label(header.label)))
            rec.close()
        elif imglist is not None:
            for img, label in imglist:
                self._records.append(
                    (np.asarray(img), np.asarray(label, np.float32)
                     .reshape(-1, 5)))
        else:
            raise MXNetError("need path_imgrec or imglist")
        if not self._records:
            raise MXNetError("no records")
        self._max_objs = max(1, max(len(b) for _i, b in self._records))
        self._order = np.arange(len(self._records))
        self.reset()

    @staticmethod
    def _parse_label(label):
        label = np.asarray(label, np.float32).ravel()
        if label.size < 2:
            return np.zeros((0, 5), np.float32)
        header_w = int(label[0])
        obj_w = int(label[1])
        body = label[header_w:]
        n = body.size // obj_w
        objs = body[:n * obj_w].reshape(n, obj_w)
        return objs[:, :5].astype(np.float32)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size, self._max_objs, 5))]

    def reset(self):
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._records):
            raise StopIteration
        idxs = self._order[self._cursor:self._cursor + self.batch_size]
        pad = self.batch_size - len(idxs)
        if pad:
            idxs = np.concatenate([idxs, self._order[:pad]])
        self._cursor += self.batch_size
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = -np.ones((self.batch_size, self._max_objs, 5), np.float32)
        for bi, ri in enumerate(idxs):
            raw, boxes = self._records[ri]
            img = imdecode_raw(raw) if isinstance(raw, bytes) else raw
            # copy: augmenters return views and normalization is in-place;
            # the cached record must never mutate across epochs
            img = np.array(img, np.float32, copy=True)
            for aug in self._aug:
                img, boxes = aug(img, boxes, self._rng)
            if img.shape[:2] != (h, w):
                img = imresize(img, w, h)
            chw = np.transpose(np.asarray(img, np.float32), (2, 0, 1))
            if self._mean is not None:
                chw -= self._mean.reshape(-1, 1, 1)
            if self._std is not None:
                chw /= self._std.reshape(-1, 1, 1)
            data[bi] = chw
            n = min(len(boxes), self._max_objs)
            if n:
                labels[bi, :n] = boxes[:n]
        return DataBatch(data=[NDArray(data)], label=[NDArray(labels)],
                         pad=pad)
