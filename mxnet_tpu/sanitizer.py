"""Runtime buffer-donation sanitizer (``MXNET_SANITIZE_DONATION=1``).

The hot paths donate their parameter/optimizer-state buffers to XLA
(``jax.jit(..., donate_argnums=...)`` in ``gluon/trainer.py``,
``gluon/step_fusion.py`` and the per-param update in ``optimizer``):
after the donating call dispatches, the old device buffers are dead and
any NDArray still holding one is a stale view.  Reading it today fails
with XLA's generic "Array has been deleted" (backends that honour
donation) or silently returns stale data (backends that ignore it).
This module upgrades that to a *precise*, deterministic error naming
the donating call site — the dependency-engine discipline the MXNet
blueprint enforced at runtime (SURVEY §2.1), recovered as a sanitizer.

Design (same contract as telemetry's null path — near-zero when off):

* ``_enabled`` is a module global read unlocked on every fast path;
  every public recorder/checker starts with ``if not _enabled: return``.
  Callers in per-op code guard with ``if sanitizer._enabled:`` so the
  disabled cost is one attribute load and a falsy branch.
* Donation is tracked **per raw buffer**, not per NDArray handle: the
  donating call paths register the raw ``jax.Array`` objects they
  donated (``donate(raws, site)``) keyed by ``id`` with a weakref
  guarding against id reuse, so *every* NDArray sharing that buffer —
  including ``detach()``/``_alias()`` views created before the call —
  is poisoned.  ``NDArray._donated`` surfaces the poison flag.
* Rebinding clears the poison by construction: the donating paths
  commit fresh result buffers into the same NDArray holders
  (``optimizer._commit_param_updates`` / ``_commit_state``), and a
  fresh buffer has no registry entry.  No clearing pass is needed and
  stale *aliases* stay poisoned — exactly the reads that are wrong.

Static counterpart: ``tools/lint`` rules T6 (use-after-donation) and
T7 (donation aliasing) prove the same contract at review time; this
sanitizer catches what escapes the analyzer (dynamic call chains,
user-held views) at run time.  See docs/lint.md and
docs/observability.md.
"""
from __future__ import annotations

import os
import threading
import weakref

from .base import MXNetError

__all__ = ["DonatedBufferError", "is_enabled", "enable", "disable",
           "donate", "site_of", "check", "reset",
           "wrap_lock", "locks_enabled", "enable_locks", "disable_locks",
           "reset_locks", "lock_order_edges", "lock_order_violations",
           "held_blocking_events", "set_trace_hook",
           "retrace", "RetraceError"]


def __getattr__(name):
    # the recompile sanitizer (MXNET_SANITIZE_RETRACE) lives with the
    # other one-boolean-null-path tiers in telemetry/retrace.py;
    # re-exported here so every runtime sanitizer is reachable from one
    # module.  Resolved lazily: telemetry.fleet wraps its lock through
    # THIS module at import time, so an eager import would be circular.
    if name == "retrace":
        from .telemetry import retrace as _retrace
        return _retrace
    if name == "RetraceError":
        from .telemetry.retrace import RetraceError as _err
        return _err
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class DonatedBufferError(MXNetError):
    """A device buffer was read after being donated to a jitted call."""


def _env_on() -> bool:
    return os.environ.get("MXNET_SANITIZE_DONATION", "").strip().lower() \
        not in ("", "0", "false", "off", "no")


#: fast-path flag: read unlocked everywhere, flipped only by
#: enable()/disable().  Import-time autostart mirrors MXNET_TELEMETRY.
_enabled = _env_on()

#: id(raw jax.Array) -> (weakref-or-None, site str).  The weakref both
#: auto-evicts entries when the dead buffer's python handle goes away
#: and guards the id against reuse by a new allocation.
_donated = {}


def is_enabled() -> bool:
    return _enabled


def enable():
    """Turn the sanitizer on (tests; production uses the env var)."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False
    _donated.clear()


def reset():
    """Forget every recorded donation (keeps the enabled state)."""
    _donated.clear()


def donate(raws, site: str):
    """Record that the buffers in ``raws`` were donated at ``site``.

    Called by the donating dispatch paths right after handing the raw
    arrays to a ``donate_argnums`` jitted callable.  ``None`` entries
    (absent masters) are skipped; non-weakref-able objects (tracers
    under nested tracing) are registered without the reuse guard.
    """
    if not _enabled:
        return
    for raw in raws:
        if raw is None:
            continue
        key = id(raw)
        try:
            ref = weakref.ref(raw, lambda _r, _k=key: _donated.pop(_k, None))
        except TypeError:
            ref = None
        _donated[key] = (ref, site)


def site_of(raw):
    """The donation site string for ``raw``, or None if it is live."""
    entry = _donated.get(id(raw))
    if entry is None:
        return None
    ref, site = entry
    if ref is not None and ref() is not raw:
        # the donated buffer was collected and its id recycled by a new,
        # live array — drop the stale entry
        _donated.pop(id(raw), None)
        return None
    return site


def check(raw, op: str = "read"):
    """Raise DonatedBufferError if ``raw`` was donated.

    Callers guard with ``if sanitizer._enabled:`` so the disabled path
    never even enters this function.
    """
    site = site_of(raw)
    if site is not None:
        raise DonatedBufferError(
            f"NDArray {op}: buffer used after donation at {site}. "
            "The buffer was handed to XLA via donate_argnums and is no "
            "longer valid; re-read the value from its owner (e.g. "
            "param.data()) after the donating call, or .copy() the array "
            "before it.  (Detected by MXNET_SANITIZE_DONATION=1; see "
            "docs/lint.md T6/T7 for the donation contract.)")


# ---------------------------------------------------------------------------
# Lock-order sanitizer (``MXNET_SANITIZE_LOCKS=1``)
# ---------------------------------------------------------------------------
# Runtime twin of mxlint's T10/T11 (tools/lint/concurrency.py): the
# package's named locks are wrapped in :class:`_SanLock`, which — when
# enabled — records per-thread held-lock stacks, the observed
# acquisition-order edges (held -> acquired), and held-while-blocking
# events (acquiring a contended lock while already holding one).  A
# cycle in the observed edge set is a lock-order violation: two threads
# took the same locks in opposite orders and a deadlock is one bad
# schedule away.  The static analyzer computes the same graph from the
# AST; lock names here match its identities (``engine._SEG_LOCK``,
# ``lanes.DecodeLane._hand_lock``) so the two graphs union and
# cross-check (tests/test_race.py).
#
# Disabled cost (the default): ``acquire``/``release``/``__enter__``/
# ``__exit__`` check one module-global boolean and delegate — the
# telemetry-null-path contract, pinned by the overhead-bound test in
# tests/test_sanitizer_locks.py.
#
# ``set_trace_hook`` exposes the acquire/acquired/released event stream;
# tools/race.py attaches here to park threads at lock boundaries and
# drive a chosen interleaving deterministically.


def _locks_env_on() -> bool:
    return os.environ.get("MXNET_SANITIZE_LOCKS", "").strip().lower() \
        not in ("", "0", "false", "off", "no")


#: fast-path flag: read unlocked in every _SanLock method, flipped only
#: by enable_locks()/disable_locks().
_locks_enabled = _locks_env_on()

#: guards the registries below (never wrapped itself)
_locks_lock = threading.Lock()

#: (src name, dst name) -> first-observed site "thread-name"
_order_edges = {}

#: held-while-blocking events: (held name, wanted name, thread name)
_blocked_events = []

#: optional callable(event, lock_name) with event in
#: {"acquire", "acquired", "released"}; called OUTSIDE _locks_lock
_trace_hook = None

#: per-thread stack of _SanLock names currently held
_held = threading.local()


def _held_stack():
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def locks_enabled() -> bool:
    return _locks_enabled


def enable_locks():
    """Turn the lock sanitizer on (tests; production uses the env var)."""
    global _locks_enabled
    _locks_enabled = True


def disable_locks():
    global _locks_enabled
    _locks_enabled = False


def reset_locks():
    """Forget every recorded edge/event (keeps the enabled state)."""
    with _locks_lock:
        _order_edges.clear()
        del _blocked_events[:]


def set_trace_hook(cb):
    """Install (or clear, with None) the acquire-event hook.  Returns
    the previous hook.  Used by tools/race.py to serialize threads at
    lock boundaries."""
    global _trace_hook
    prev = _trace_hook
    _trace_hook = cb
    return prev


def lock_order_edges():
    """``{(src, dst): site}`` — every observed held->acquired pair."""
    with _locks_lock:
        return dict(_order_edges)


def held_blocking_events():
    """Events where a thread blocked on a contended lock while already
    holding one — the dynamic half of T11's blocking-under-lock."""
    with _locks_lock:
        return list(_blocked_events)


def lock_order_violations():
    """Cycles in the observed acquisition-order graph, as a list of
    ``[name, name, ...]`` chains (empty == discipline held)."""
    edges = lock_order_edges()
    adj = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    cycles = []
    seen = set()
    for start in sorted(adj):
        stack = [(start, (start,))]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(path) + [start])
                elif nxt not in path and len(path) < 16:
                    stack.append((nxt, path + (nxt,)))
    return cycles


class _SanLock:
    """Instrumentation proxy around a ``threading`` lock/condition.

    Delegates everything to the wrapped primitive; when the sanitizer
    is enabled, acquisition records order edges against the calling
    thread's held stack.  ``wait``/``wait_for`` (Condition protocol)
    pop the lock around the wait — the condition releases it — so the
    held stack mirrors reality."""

    __slots__ = ("_lock", "name")

    def __init__(self, lock, name):
        self._lock = lock
        self.name = name

    # -- instrumented core ---------------------------------------------------
    def acquire(self, *args, **kwargs):
        if not _locks_enabled:
            return self._lock.acquire(*args, **kwargs)
        return self._acquire_traced(args, kwargs)

    def _acquire_traced(self, args, kwargs):
        hook = _trace_hook
        if hook is not None:
            hook("acquire", self.name)
        stack = _held_stack()
        if stack and self._locked():
            with _locks_lock:
                _blocked_events.append(
                    (stack[-1], self.name,
                     threading.current_thread().name))
        ok = self._lock.acquire(*args, **kwargs)
        if ok:
            if stack:
                site = threading.current_thread().name
                with _locks_lock:
                    for h in stack:
                        if h != self.name:
                            _order_edges.setdefault((h, self.name), site)
            stack.append(self.name)
            if hook is not None:
                hook("acquired", self.name)
        return ok

    def release(self):
        if not _locks_enabled:
            return self._lock.release()
        stack = _held_stack()
        if self.name in stack:
            stack.remove(self.name)
        self._lock.release()
        hook = _trace_hook
        if hook is not None:
            hook("released", self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _locked(self):
        probe = getattr(self._lock, "locked", None)
        if probe is None:
            return False
        try:
            return bool(probe())
        except TypeError:
            return False

    # -- Condition protocol --------------------------------------------------
    def wait(self, timeout=None):
        if not _locks_enabled:
            return self._lock.wait(timeout)
        stack = _held_stack()
        popped = self.name in stack
        if popped:
            stack.remove(self.name)
        try:
            return self._lock.wait(timeout)
        finally:
            if popped:
                stack.append(self.name)

    def wait_for(self, predicate, timeout=None):
        if not _locks_enabled:
            return self._lock.wait_for(predicate, timeout)
        stack = _held_stack()
        popped = self.name in stack
        if popped:
            stack.remove(self.name)
        try:
            return self._lock.wait_for(predicate, timeout)
        finally:
            if popped:
                stack.append(self.name)

    def __getattr__(self, attr):
        # notify/notify_all/locked/_is_owned/... delegate untouched
        return getattr(self._lock, attr)

    def __repr__(self):
        return f"<_SanLock {self.name} wrapping {self._lock!r}>"


def wrap_lock(lock, name: str):
    """Wrap a ``threading`` lock/RLock/Condition for the lock
    sanitizer.  ``name`` must match the static analyzer's identity for
    the lock — ``module.GLOBAL_NAME`` or ``module.Class.attr`` — so the
    runtime and static order graphs line up.  The proxy is always
    returned (construction cost is two slot writes); with the sanitizer
    disabled every operation is one boolean check plus delegation."""
    return _SanLock(lock, name)
