"""Runtime buffer-donation sanitizer (``MXNET_SANITIZE_DONATION=1``).

The hot paths donate their parameter/optimizer-state buffers to XLA
(``jax.jit(..., donate_argnums=...)`` in ``gluon/trainer.py``,
``gluon/step_fusion.py`` and the per-param update in ``optimizer``):
after the donating call dispatches, the old device buffers are dead and
any NDArray still holding one is a stale view.  Reading it today fails
with XLA's generic "Array has been deleted" (backends that honour
donation) or silently returns stale data (backends that ignore it).
This module upgrades that to a *precise*, deterministic error naming
the donating call site — the dependency-engine discipline the MXNet
blueprint enforced at runtime (SURVEY §2.1), recovered as a sanitizer.

Design (same contract as telemetry's null path — near-zero when off):

* ``_enabled`` is a module global read unlocked on every fast path;
  every public recorder/checker starts with ``if not _enabled: return``.
  Callers in per-op code guard with ``if sanitizer._enabled:`` so the
  disabled cost is one attribute load and a falsy branch.
* Donation is tracked **per raw buffer**, not per NDArray handle: the
  donating call paths register the raw ``jax.Array`` objects they
  donated (``donate(raws, site)``) keyed by ``id`` with a weakref
  guarding against id reuse, so *every* NDArray sharing that buffer —
  including ``detach()``/``_alias()`` views created before the call —
  is poisoned.  ``NDArray._donated`` surfaces the poison flag.
* Rebinding clears the poison by construction: the donating paths
  commit fresh result buffers into the same NDArray holders
  (``optimizer._commit_param_updates`` / ``_commit_state``), and a
  fresh buffer has no registry entry.  No clearing pass is needed and
  stale *aliases* stay poisoned — exactly the reads that are wrong.

Static counterpart: ``tools/lint`` rules T6 (use-after-donation) and
T7 (donation aliasing) prove the same contract at review time; this
sanitizer catches what escapes the analyzer (dynamic call chains,
user-held views) at run time.  See docs/lint.md and
docs/observability.md.
"""
from __future__ import annotations

import os
import weakref

from .base import MXNetError

__all__ = ["DonatedBufferError", "is_enabled", "enable", "disable",
           "donate", "site_of", "check", "reset"]


class DonatedBufferError(MXNetError):
    """A device buffer was read after being donated to a jitted call."""


def _env_on() -> bool:
    return os.environ.get("MXNET_SANITIZE_DONATION", "").strip().lower() \
        not in ("", "0", "false", "off", "no")


#: fast-path flag: read unlocked everywhere, flipped only by
#: enable()/disable().  Import-time autostart mirrors MXNET_TELEMETRY.
_enabled = _env_on()

#: id(raw jax.Array) -> (weakref-or-None, site str).  The weakref both
#: auto-evicts entries when the dead buffer's python handle goes away
#: and guards the id against reuse by a new allocation.
_donated = {}


def is_enabled() -> bool:
    return _enabled


def enable():
    """Turn the sanitizer on (tests; production uses the env var)."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False
    _donated.clear()


def reset():
    """Forget every recorded donation (keeps the enabled state)."""
    _donated.clear()


def donate(raws, site: str):
    """Record that the buffers in ``raws`` were donated at ``site``.

    Called by the donating dispatch paths right after handing the raw
    arrays to a ``donate_argnums`` jitted callable.  ``None`` entries
    (absent masters) are skipped; non-weakref-able objects (tracers
    under nested tracing) are registered without the reuse guard.
    """
    if not _enabled:
        return
    for raw in raws:
        if raw is None:
            continue
        key = id(raw)
        try:
            ref = weakref.ref(raw, lambda _r, _k=key: _donated.pop(_k, None))
        except TypeError:
            ref = None
        _donated[key] = (ref, site)


def site_of(raw):
    """The donation site string for ``raw``, or None if it is live."""
    entry = _donated.get(id(raw))
    if entry is None:
        return None
    ref, site = entry
    if ref is not None and ref() is not raw:
        # the donated buffer was collected and its id recycled by a new,
        # live array — drop the stale entry
        _donated.pop(id(raw), None)
        return None
    return site


def check(raw, op: str = "read"):
    """Raise DonatedBufferError if ``raw`` was donated.

    Callers guard with ``if sanitizer._enabled:`` so the disabled path
    never even enters this function.
    """
    site = site_of(raw)
    if site is not None:
        raise DonatedBufferError(
            f"NDArray {op}: buffer used after donation at {site}. "
            "The buffer was handed to XLA via donate_argnums and is no "
            "longer valid; re-read the value from its owner (e.g. "
            "param.data()) after the donating call, or .copy() the array "
            "before it.  (Detected by MXNET_SANITIZE_DONATION=1; see "
            "docs/lint.md T6/T7 for the donation contract.)")
