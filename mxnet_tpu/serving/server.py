"""The user-facing serving surface: configs, servers, lifecycle.

Two server classes over one contract (bounded queue → scheduler thread
→ per-request futures, docs/serving.md):

* :class:`InferenceServer` — stateless models (one forward per
  request): a ``Predictor`` (the MXPredCreate surface), a hybridized
  gluon block (e.g. BERT), or any callable.  Dynamic batching with
  power-of-two batch/length buckets.
* :class:`GenerativeServer` — ``LlamaForCausalLM`` decode with the
  sliced KV cache: requests join and leave the in-flight decode batch
  between steps (continuous batching).

``ServerConfig(int8=True)`` applies weight quantization at load time:
gluon blocks go through ``contrib.quantization.quantize_net`` (needs
``calib_data``); the llama engine uses weight-only per-channel int8.

Synchronous convenience: ``server.infer(...)`` / ``server.generate(...)``
submit and wait (the future's ``result()`` is the sanctioned eager wait,
same contract as async-checkpoint tickets).
"""
from __future__ import annotations

import time

import numpy as np

from .. import telemetry
from ..telemetry import capacity
from ..telemetry import tracing
from ..base import MXNetError
from .bucketing import BucketPolicy
from .protocol import Request, ServerClosedError, ServerOverloadedError
from .scheduler import BatchScheduler, RequestQueue

__all__ = ["ServerConfig", "InferenceServer", "GenerativeServer"]


class ServerConfig:
    """Knobs shared by both servers (defaults are test-scale).

    ``max_batch``/``max_length`` bound the bucket grid — the compiled-
    signature ceiling is ``len(batch_buckets) × len(length_buckets)``.
    ``queue_capacity`` bounds admission (beyond it, submit raises
    ``ServerOverloadedError``).  ``length_axis`` names the bucketed
    axis of each request's input arrays; ``output_length_axis`` (may be
    None) the per-example output axis to trim back at demux.
    ``num_slots`` (generative) is the KV-cache capacity = max
    concurrent sequences; ``int8`` switches on load-time weight
    quantization."""

    def __init__(self, max_batch=8, max_length=128, min_batch=1,
                 min_length=8, queue_capacity=64, batch_window_ms=2.0,
                 summary_every=32, length_axis=0, output_length_axis=None,
                 num_slots=4, max_new_tokens=32, int8=False,
                 calib_data=None, kv_mode="paged", block_size=16,
                 num_blocks=None, http_port=None, http_host="127.0.0.1",
                 slo=None, slo_window=256, draft_net=None, spec_k=3,
                 radix_cache=False, prefix_cache_tokens=None):
        self.policy = BucketPolicy(max_batch=max_batch,
                                   max_length=max_length,
                                   min_batch=min_batch,
                                   min_length=min_length)
        self.queue_capacity = int(queue_capacity)
        self.batch_window_ms = float(batch_window_ms)
        self.summary_every = int(summary_every)
        self.length_axis = int(length_axis)
        self.output_length_axis = output_length_axis
        self.num_slots = int(num_slots)
        self.max_new_tokens = int(max_new_tokens)
        self.int8 = bool(int8)
        self.calib_data = calib_data
        # generative KV storage: "paged" (block pool + disaggregated
        # prefill/decode lanes, the default) or "slots" (the r8 ledger
        # + single-loop scheduler, kept for A/B).  ``num_blocks=None``
        # sizes the pool at ledger parity (num_slots × max_len tokens);
        # smaller pools bound capacity by tokens in flight instead.
        if kv_mode not in ("paged", "slots"):
            raise MXNetError(f"unknown kv_mode {kv_mode!r}; "
                             "expected 'paged' or 'slots'")
        self.kv_mode = kv_mode
        self.block_size = int(block_size)
        self.num_blocks = num_blocks
        # observability (r12): ``http_port`` starts the live metrics
        # endpoint with the server (0 = ephemeral port, read it back
        # from ``server.metrics_url``); ``slo`` maps tenant →
        # {"ttft_ms": x, "tpot_ms": y} targets (a flat dict is the
        # "default" tenant) for goodput accounting over ``slo_window``
        # recent requests (docs/observability.md).
        self.http_port = http_port if http_port is None else int(http_port)
        self.http_host = str(http_host)
        self.slo = slo
        self.slo_window = int(slo_window)
        # speculative decoding + radix prefix cache (r19, paged only):
        # ``draft_net`` switches speculation on (the small proposer
        # model; ``spec_k`` proposals per slot per verify), and
        # ``radix_cache`` turns on prompt-prefix KV reuse with an LRU
        # budget of ``prefix_cache_tokens`` (None = half the pool).
        self.draft_net = draft_net
        self.spec_k = int(spec_k)
        self.radix_cache = bool(radix_cache)
        self.prefix_cache_tokens = prefix_cache_tokens \
            if prefix_cache_tokens is None else int(prefix_cache_tokens)


class _ServerBase:
    """start/stop/context-manager scaffolding shared by both servers,
    plus the r12 observability surface: the metrics endpoint lifecycle,
    the shared SLO tracker, and trace creation at submit."""

    def __init__(self, config):
        self.config = config or ServerConfig()
        self.queue = RequestQueue(self.config.queue_capacity)
        self._running = False
        self._metrics = None
        self.slo = None
        if self.config.slo:
            from .metrics import SLOTracker

            self.slo = SLOTracker(self.config.slo,
                                  window=self.config.slo_window)

    def start(self):
        self._sched.start()
        self._running = True
        self._start_http()
        return self

    def stop(self, drain=True):
        """Graceful by default: queued work is served before exit."""
        if not self._running:
            return
        self._running = False
        self._stop_http()
        self._sched.stop(drain=drain)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- metrics endpoint -----------------------------------------------------
    def _start_http(self):
        if self.config.http_port is None or self._metrics is not None:
            return
        from .metrics import MetricsServer

        self._metrics = MetricsServer(
            self, host=self.config.http_host,
            port=self.config.http_port).start()

    def _stop_http(self):
        if self._metrics is not None:
            self._metrics.stop()
            self._metrics = None

    @property
    def metrics_url(self):
        """Base URL of the live endpoint (None when not started)."""
        return self._metrics.url if self._metrics is not None else None

    def metrics_gauges(self):
        """Live gauges the /metrics scrape adds on top of the telemetry
        snapshot (subclasses extend)."""
        return {"serving.queue_depth": len(self.queue),
                "serving.rejected_total": self.queue.rejected}

    # -- submission -----------------------------------------------------------
    def _submit(self, req):
        if not self._running:
            raise ServerClosedError("server is not running; call start()")
        if tracing.is_enabled() and req.trace is None:
            req.trace = tracing.start_trace(request_id=req.id,
                                            tenant=req.tenant)
        try:
            self.queue.put(req)
        except ServerOverloadedError as exc:
            # shed-load accounting: the rejected request still lands in
            # the JSONL stream (tagged) and trips the flight recorder
            telemetry.emit(req.record(lane="queue", status="rejected",
                                      error=repr(exc)))
            if req.trace is not None:
                tracing.finish(req.trace, status="rejected", lane="queue",
                               error=repr(exc), request_id=req.id)
                req.trace = None
            tracing.incident("overload_rejection", context={
                "queue_capacity": self.queue.capacity,
                "rejected": self.queue.rejected})
            raise
        return req.future


class InferenceServer(_ServerBase):
    """Dynamic-batching server for stateless models.

    ``model`` may be a ``Predictor``, a gluon block, or a callable
    taking a dict of stacked numpy arrays and returning outputs.
    ``input_names`` orders multi-input models (defaults to the
    Predictor's own input names, or ``["data"]``).
    """

    def __init__(self, model, config=None, input_names=None):
        super().__init__(config)
        self.model = model
        self._predictor = model if hasattr(model, "forward") and \
            hasattr(model, "input_names") else None
        if input_names is None:
            input_names = self._predictor.input_names \
                if self._predictor is not None else ["data"]
        self.input_names = list(input_names)
        if self.config.int8 and self._predictor is None and \
                hasattr(model, "collect_params"):
            from ..contrib.quantization import quantize_net

            if self.config.calib_data is None:
                raise MXNetError(
                    "int8 block serving needs config.calib_data for "
                    "calibration")
            self.model = quantize_net(model,
                                      calib_data=self.config.calib_data,
                                      calib_mode="naive")
        self._sched = BatchScheduler(
            self._run_batch, self.config.policy, self.queue,
            length_axis=self.config.length_axis,
            output_length_axis=self.config.output_length_axis,
            batch_window_ms=self.config.batch_window_ms,
            summary_every=self.config.summary_every)

    def _run_batch(self, batch):
        """One padded bucket through the model (scheduler thread)."""
        from .. import ndarray as nd

        if self._predictor is not None:
            return self._predictor.forward(**batch)
        if callable(self.model) and not hasattr(self.model,
                                                "collect_params"):
            return self.model(batch)
        args = [nd.array(batch[n]) for n in self.input_names]
        out = self.model(*args)
        return out if isinstance(out, (list, tuple)) else [out]

    # -- client surface -------------------------------------------------------
    def submit(self, inputs, length=None, tenant=None):
        """Async: one example's inputs (array, or dict name → array) →
        a Future resolving to the demuxed output(s).  ``length`` is the
        true size of the bucketed axis (defaults to the first input's
        ``length_axis`` extent)."""
        if not isinstance(inputs, dict):
            inputs = {self.input_names[0]: inputs}
        inputs = {k: np.asarray(v) for k, v in inputs.items()}
        if length is None:
            length = inputs[self.input_names[0]] \
                .shape[self.config.length_axis]
        req = Request(inputs=inputs, length=int(length), tenant=tenant)
        return self._submit(req)

    def infer(self, inputs, length=None, timeout=60.0):
        """Sync: submit + wait."""
        return self.submit(inputs, length=length).result(timeout)

    def health(self):
        """The /healthz body: scheduler-thread liveness + queue depth
        (host-side snapshot, never a device touch)."""
        alive = self._sched._thread is not None \
            and self._sched._thread.is_alive()
        if not self._running:
            status = "stopped"
        else:
            status = "ok" if alive else "degraded"
        return {"status": status, "running": self._running,
                "scheduler_alive": alive,
                "queue_depth": len(self.queue),
                "rejected": self.queue.rejected}

    def in_flight(self):
        """The /requests table: currently queued requests."""
        with self.queue._cond:
            items = list(self.queue._items)
        now = time.perf_counter()
        return [{"request_id": r.id, "state": "queued",
                 "length": r.length, "tenant": r.tenant,
                 "trace_id": r.trace.trace_id
                 if r.trace is not None else None,
                 "age_ms": round((now - r.t_submit) * 1e3, 3)}
                for r in items]

    def stats(self):
        """Server + compile-cache counters (the bucketing-policy
        verification surface)."""
        out = {
            "completed": self._sched.completed,
            "failed": self._sched.failed,
            "batches": self._sched.batches,
            "rejected": self.queue.rejected,
            "pending": len(self.queue),
            "signature_ceiling": len(self.config.policy.signatures()),
        }
        if self._predictor is not None:
            out["cache"] = self._predictor.cache_stats()
        elif hasattr(self.model, "_cached_op") and \
                self.model._cached_op is not None:
            out["cache"] = self.model._cached_op.cache_stats()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        return out


def _split_mesh(mesh, dp_axis="dp"):
    """One submesh per dp replica: slice ``dp_axis`` off and keep the
    remaining axes (tp, ...) per slice, so each replica's engine is an
    ordinary tensor-parallel engine over its own devices.  No mesh →
    ``[None]`` (single default-device replica); no dp axis → the whole
    mesh is one replica."""
    if mesh is None:
        return [None]
    if dp_axis not in mesh.axis_names:
        return [mesh]
    from jax.sharding import Mesh

    axis = mesh.axis_names.index(dp_axis)
    rest = tuple(a for a in mesh.axis_names if a != dp_axis)
    devs = np.moveaxis(mesh.devices, axis, 0)
    if not rest:
        # dp-only mesh: each replica is a single-device tp=1 mesh so
        # its weights still commit to ITS device, not the default one
        return [Mesh(np.asarray(devs[i]).reshape(1), ("tp",))
                for i in range(devs.shape[0])]
    return [Mesh(devs[i], rest) for i in range(devs.shape[0])]


class GenerativeServer(_ServerBase):
    """Continuous-batching decode server for ``LlamaForCausalLM``.

    Mesh-native: ``mesh=`` places the weights (and the KV pool)
    tensor-parallel per ``partition_rules=`` (default: the
    ``"llama_serving"`` family table) exactly like ``Trainer`` does for
    training; a ``dp`` mesh axis runs one independent replica per dp
    slice behind this one front queue, routed least-loaded by
    :class:`~.lanes.ReplicaDispatcher`.  ``config.kv_mode`` selects the
    paged block-pool storage with disaggregated prefill/decode lanes
    (default) or the legacy r8 slot ledger + single-loop scheduler
    (``"slots"``, A/B baseline; single replica only).
    """

    def __init__(self, net, config=None, mesh=None, partition_rules=None):
        super().__init__(config)
        from .generative import GenerativeScheduler, LlamaServingEngine
        from .lanes import Replica, ReplicaDispatcher

        cfg = self.config
        self.mesh = mesh
        self._replicas = None
        self._dispatcher = None
        if cfg.kv_mode == "slots":
            if mesh is not None and "dp" in mesh.axis_names:
                raise MXNetError(
                    "kv_mode='slots' runs the single-loop scheduler; "
                    "dp replicas need kv_mode='paged'")
            if cfg.draft_net is not None or cfg.radix_cache:
                raise MXNetError(
                    "speculative decoding and the radix prefix cache "
                    "require kv_mode='paged'")
            self.engine = LlamaServingEngine(
                net, max_len=cfg.policy.max_length,
                num_slots=cfg.num_slots, int8=cfg.int8,
                kv_mode="slots", mesh=mesh,
                partition_rules=partition_rules)
            self._sched = GenerativeScheduler(
                self.engine, self.queue, policy=cfg.policy,
                summary_every=cfg.summary_every, slo=self.slo)
            return
        self._replicas = [
            Replica(net, cfg.policy, index=i, mesh=sub,
                    partition_rules=partition_rules,
                    num_slots=cfg.num_slots, int8=cfg.int8,
                    block_size=cfg.block_size, num_blocks=cfg.num_blocks,
                    queue_capacity=cfg.queue_capacity,
                    summary_every=cfg.summary_every, slo=self.slo,
                    draft_net=cfg.draft_net, spec_k=cfg.spec_k,
                    radix_cache=cfg.radix_cache,
                    prefix_cache_tokens=cfg.prefix_cache_tokens)
            for i, sub in enumerate(_split_mesh(mesh))]
        self._dispatcher = ReplicaDispatcher(self.queue, self._replicas)
        self.engine = self._replicas[0].engine
        self._sched = None

    @property
    def replicas(self):
        return self._replicas or []

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        if self._replicas is None:
            return super().start()
        # fresh ledgers per server lifetime: replica indices restart at
        # 0, so a previous server's estimators must not leak in
        capacity.reset()
        for rep in self._replicas:
            rep.start()
        self._dispatcher.start()
        self._running = True
        self._start_http()
        return self

    def stop(self, drain=True):
        if not self._running:
            return
        self._running = False
        self._stop_http()
        if self._replicas is None:
            self._sched.stop(drain=drain)
            return
        # flush the front queue into the replicas first, then drain
        # each replica (prefill lane before decode lane)
        self._dispatcher.stop(drain=drain)
        for rep in self._replicas:
            rep.stop(drain=drain)

    # -- client surface -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, tenant=None):
        """Async: 1-D prompt token ids → Future resolving to the full
        sequence (prompt + generated), greedy decode.  ``tenant`` keys
        the request's SLO targets (config.slo)."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = int(max_new_tokens or self.config.max_new_tokens)
        if n < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if len(prompt) + n > self.engine.max_len:
            raise MXNetError(
                f"prompt {len(prompt)} + {n} new tokens exceeds the "
                f"engine's max_len {self.engine.max_len}")
        req = Request(prompt_ids=prompt, max_new_tokens=n, tenant=tenant)
        req.length = len(prompt)
        return self._submit(req)

    def generate(self, prompt_ids, max_new_tokens=None, timeout=120.0):
        """Sync: submit + wait for the full sequence."""
        return self.submit(prompt_ids, max_new_tokens).result(timeout)

    # -- observability surface ------------------------------------------------
    def health(self):
        """The /healthz body: per-replica lane liveness, queue depths,
        and KV occupancy/fragmentation — every number a host-side
        counter read, never a device touch.  ``status`` is ``"ok"``
        only when every lane thread is alive."""
        if self._replicas is None:
            alive = self._sched._thread is not None \
                and self._sched._thread.is_alive()
            kv = self._sched.mgr.stats()
            if not self._running:
                status = "stopped"
            else:
                status = "ok" if alive else "degraded"
            return {"status": status, "running": self._running,
                    "scheduler_alive": alive,
                    "queue_depth": len(self.queue),
                    "rejected": self.queue.rejected,
                    "kv_occupancy": kv["occupancy"],
                    "kv_utilization": kv["utilization"],
                    "kv_fragmentation": kv["fragmentation"]}
        reps = []
        all_alive = True
        any_saturated = False
        for r in self._replicas:
            kv = r.mgr.stats()
            pa, da = r.prefill.alive(), r.decode.alive()
            all_alive = all_alive and pa and da
            row = {
                "replica": r.index,
                "prefill_alive": pa,
                "decode_alive": da,
                "queue_depth": len(r.queue),
                "in_flight": kv["occupancy"],
                "failed": r.failed,
                "kv_utilization": kv["utilization"],
                "kv_fragmentation": kv["fragmentation"],
                "kv_blocks_in_use": kv["blocks_in_use"]}
            cap = capacity.snapshot(r.index)
            if cap is not None:
                row["saturated"] = cap["saturated"]
                row["rho"] = cap["rho"]
                row["headroom_rps"] = cap["headroom_rps"]
                any_saturated = any_saturated or cap["saturated"]
            reps.append(row)
        if not self._running:
            status = "stopped"
        elif not all_alive:
            status = "degraded"
        elif any_saturated:
            # degraded-but-alive: every lane is serving, but ρ sits
            # above threshold — still HTTP 200 (a readiness probe must
            # not kill a replica for being busy; the control plane
            # reads headroom, not liveness)
            status = "saturated"
        else:
            status = "ok"
        return {"status": status, "running": self._running,
                "queue_depth": len(self.queue),
                "rejected": self.queue.rejected,
                "replicas": reps}

    def in_flight(self):
        """The /requests table: every request currently queued (front
        queue + replica queues) or decoding, with ids the trace stream
        can be joined on."""
        now = time.perf_counter()

        def queued(queue, replica=None):
            with queue._cond:
                items = list(queue._items)
            return [{"request_id": r.id, "state": "queued",
                     "replica": replica, "length": r.length,
                     "tenant": r.tenant,
                     "trace_id": r.trace.trace_id
                     if r.trace is not None else None,
                     "age_ms": round((now - r.t_submit) * 1e3, 3)}
                    for r in items]

        rows = queued(self.queue)
        if self._replicas is None:
            for slot, (req, tokens) in list(self._sched._seqs.items()):
                rows.append({"request_id": req.id, "state": "decoding",
                             "replica": req.replica, "slot": slot,
                             "tenant": req.tenant,
                             "trace_id": req.trace.trace_id
                             if req.trace is not None else None,
                             "tokens_done": len(tokens),
                             "max_new_tokens": req.max_new_tokens})
            return rows
        for r in self._replicas:
            rows.extend(queued(r.queue, replica=r.index))
            rows.extend(r.decode.snapshot())
        return rows

    def metrics_gauges(self):
        """Extend the base scrape gauges with live KV-pool state —
        per replica when there are several."""
        out = super().metrics_gauges()
        if self._replicas is None:
            kv = self._sched.mgr.stats()
            out["serving.kv_occupancy"] = kv["occupancy"]
            out["serving.kv_utilization"] = kv["utilization"]
            out["serving.kv_fragmentation"] = kv["fragmentation"]
            return out
        drafted = accepted = 0
        for r in self._replicas:
            kv = r.mgr.stats()
            tag = f"|replica={r.index}"
            out["serving.kv_occupancy" + tag] = kv["occupancy"]
            out["serving.kv_utilization" + tag] = kv["utilization"]
            out["serving.kv_fragmentation" + tag] = kv["fragmentation"]
            out["serving.kv_blocks_in_use" + tag] = kv["blocks_in_use"]
            out["serving.replica_queue_depth" + tag] = len(r.queue)
            if r.spec_k:
                drafted += r.draft_tokens
                accepted += r.accepted_tokens
                if r.draft_tokens:
                    out["serving.accept_rate" + tag] = round(
                        r.accepted_tokens / r.draft_tokens, 4)
            if r.radix is not None:
                rx = r.radix.stats()
                out["serving.radix_hits" + tag] = rx["hits"]
                out["serving.radix_hit_tokens" + tag] = rx["hit_tokens"]
                out["serving.radix_evictions" + tag] = rx["evictions"]
                out["serving.radix_cached_tokens" + tag] = \
                    rx["cached_tokens"]
            cap = capacity.snapshot(r.index)
            if cap is not None:
                out["serving.utilization" + tag] = cap["utilization"]
                out["serving.kv_free_frac" + tag] = cap["kv_free_frac"]
                if cap["rho"] is not None:
                    out["serving.rho" + tag] = cap["rho"]
                if cap["headroom_rps"] is not None:
                    out["serving.headroom_rps" + tag] = \
                        cap["headroom_rps"]
        if drafted:
            out["serving.accept_rate"] = round(accepted / drafted, 4)
        if capacity.is_enabled():
            # fleet-level rollup: worst ρ (the replica closest to the
            # knee governs admission) and total spare request rate
            rhos = [v for k, v in out.items()
                    if k.startswith("serving.rho|")]
            heads = [v for k, v in out.items()
                     if k.startswith("serving.headroom_rps|")]
            utils = [v for k, v in out.items()
                     if k.startswith("serving.utilization|")]
            if rhos:
                out["serving.rho"] = max(rhos)
            if heads:
                out["serving.headroom_rps"] = round(sum(heads), 4)
            if utils:
                out["serving.utilization"] = max(utils)
        return out

    def stats(self):
        if self._replicas is None:
            out = {
                "completed": self._sched.completed,
                "failed": self._sched.failed,
                "decode_steps": self.engine.steps,
                "rejected": self.queue.rejected,
                "pending": len(self.queue),
                "kv_cache": self._sched.mgr.stats(),
                "compiled_signatures": self.engine.compiled_signatures(),
            }
            telemetry.gauge("serving.kv_occupancy",
                            out["kv_cache"]["occupancy"])
            if self.slo is not None:
                out["slo"] = self.slo.snapshot()
            return out
        reps = self._replicas
        out = {
            "completed": sum(r.completed for r in reps),
            "failed": sum(r.failed for r in reps),
            "decode_steps": sum(r.engine.steps for r in reps),
            "rejected": self.queue.rejected,
            "pending": len(self.queue) + sum(len(r.queue) for r in reps),
            "kv_cache": reps[0].mgr.stats(),
            "compiled_signatures":
                reps[0].engine.compiled_signatures(),
            "num_replicas": len(reps),
        }
        if len(reps) > 1:
            out["replicas"] = [{
                "completed": r.completed,
                "failed": r.failed,
                "decode_steps": r.engine.steps,
                "kv_cache": r.mgr.stats(),
                "compiled_signatures": r.engine.compiled_signatures(),
            } for r in reps]
        if any(r.spec_k for r in reps):
            drafted = sum(r.draft_tokens for r in reps)
            accepted = sum(r.accepted_tokens for r in reps)
            out["speculative"] = {
                "k": max(r.spec_k for r in reps),
                "draft_tokens": drafted,
                "accepted_tokens": accepted,
                "accept_rate": round(accepted / drafted, 4)
                if drafted else None,
            }
            if drafted:
                telemetry.gauge("serving.accept_rate",
                                out["speculative"]["accept_rate"])
        if any(r.radix is not None for r in reps):
            rx = [r.radix.stats() for r in reps if r.radix is not None]
            out["radix_cache"] = {
                k: sum(s[k] for s in rx)
                for k in ("hits", "misses", "hit_tokens", "evictions",
                          "inserted_blocks", "cached_tokens")}
        telemetry.gauge("serving.kv_occupancy",
                        sum(r.mgr.stats()["occupancy"] for r in reps))
        telemetry.gauge("serving.kv_blocks_in_use",
                        sum(r.mgr.allocator.blocks_in_use for r in reps))
        if capacity.is_enabled():
            out["capacity"] = [capacity.snapshot(r.index) for r in reps]
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        return out
