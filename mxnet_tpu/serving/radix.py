"""Radix prefix cache: shared-prefix KV reuse over the paged pool.

The chat / RAG serving scenario sends thousands of requests that open
with the same system prompt.  Without reuse every one of them prefills
the same tokens into its own freshly allocated blocks.  This module
keeps a **trie over block-aligned prompt prefixes** (SGLang's
RadixAttention idea, at block granularity): each trie node represents
one full ``block_size``-token chunk and pins the physical paged block
holding that chunk's K/V.  A new request walks the trie over its prompt,
adopts the matched blocks by reference (``BlockAllocator.share``), and
only prefills the novel suffix.

Design points:

* **Block granularity.** Matching is in whole-block units — a physical
  block either exactly holds a request's chunk ``[i*bs, (i+1)*bs)`` or
  it is unusable, so only full blocks enter the trie (the trailing
  partial prompt block is always prefilled by its owner).  RoPE is
  applied at absolute positions before K enters the pool, so a prefix
  block's rows are bit-identical for every request sharing the prefix.
* **At least one novel token.** ``match_len`` caps the match at
  ``(prompt_len - 1)`` rounded down to a block boundary: the suffix
  prefill must process ≥ 1 real token to produce first-token logits.
* **Write-safety.** Cached blocks hold *full prompt chunks* only.  A
  request's decode/verify writes start at ``pos >= prompt_len``, which
  lies strictly past its last full prompt block, so no shared block is
  ever written — sharing is read-only by construction (no
  copy-on-write needed).
* **Refcounts, not copies.** The cache holds ONE allocator reference
  per cached block; every adopting request holds its own (taken by
  ``PagedKVCacheManager.admit(shared_blocks=...)``).  LRU eviction
  drops the cache's reference; a block still read by an active request
  survives until that request evicts (evict-while-shared is safe).
* **LRU under a token budget.**  ``insert`` registers a finished
  prefill's full prompt blocks and then evicts least-recently-matched
  *leaf* chunks until ``cached_tokens <= capacity_tokens`` (leaves
  first so every cached node stays reachable from the root).

The cache is per-replica (blocks are physical ids in the replica's own
pool).  Only the prefill lane mutates it, but all entry points take the
internal lock so ``stats()`` / ``check()`` readers from other threads
see a consistent trie.
"""
from __future__ import annotations

import itertools
import threading

from ..base import MXNetError

__all__ = ["RadixPrefixCache"]


class _Node:
    """One cached full-block chunk; children keyed by the next chunk's
    token tuple."""

    __slots__ = ("chunk", "block", "tick", "parent", "children")

    def __init__(self, chunk, block, tick, parent):
        self.chunk = chunk          # tuple of block_size token ids
        self.block = block          # physical block id (cache's ref)
        self.tick = tick            # LRU stamp, bumped on every match
        self.parent = parent
        self.children = {}


class RadixPrefixCache:
    """Trie from block-aligned prompt prefixes to refcounted paged KV
    blocks."""

    def __init__(self, allocator, block_size, capacity_tokens):
        if capacity_tokens < 0:
            raise MXNetError("capacity_tokens must be >= 0")
        self.allocator = allocator
        self.block_size = int(block_size)
        self.capacity_tokens = int(capacity_tokens)
        self._root = _Node(None, None, 0, None)
        self._nodes = 0
        self._tick = itertools.count(1)
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0
        self.inserted_blocks = 0

    # -- queries --------------------------------------------------------------
    def _chunks(self, prompt_ids, limit):
        bs = self.block_size
        n = min(len(prompt_ids), limit) // bs
        return [tuple(int(t) for t in prompt_ids[i * bs:(i + 1) * bs])
                for i in range(n)]

    def _match_cap(self, prompt_ids):
        """Longest usable match in tokens: whole blocks only, and at
        least one prompt token left novel."""
        bs = self.block_size
        return max(len(prompt_ids) - 1, 0) // bs * bs

    def _walk(self, prompt_ids):
        """(nodes, matched_tokens) for the longest cached prefix."""
        node, path = self._root, []
        for chunk in self._chunks(prompt_ids,
                                  self._match_cap(prompt_ids)):
            nxt = node.children.get(chunk)
            if nxt is None:
                break
            path.append(nxt)
            node = nxt
        return path, len(path) * self.block_size

    def match_len(self, prompt_ids):
        """Matched prefix length in tokens WITHOUT touching LRU state —
        safe for batching/bucketing decisions ahead of the real
        :meth:`lookup`."""
        with self._lock:
            return self._walk(prompt_ids)[1]

    def lookup(self, prompt_ids):
        """Longest cached prefix of ``prompt_ids``: returns
        ``(matched_tokens, blocks)`` (logical order) and freshens the
        matched path's LRU stamps.  No references are taken — the
        caller passes ``blocks`` to ``admit(shared_blocks=...)``, which
        shares them under the manager lock."""
        with self._lock:
            path, matched = self._walk(prompt_ids)
            for node in path:
                node.tick = next(self._tick)
            if matched:
                self.hits += 1
                self.hit_tokens += matched
            else:
                self.misses += 1
            return matched, [n.block for n in path]

    def cached_tokens(self):
        with self._lock:
            return self._nodes * self.block_size

    def block_refs(self):
        """block id -> 1 for every block the cache holds a reference
        on (consumed by ``PagedKVCacheManager.check()``)."""
        with self._lock:
            out = {}
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                out[node.block] = 1
                stack.extend(node.children.values())
            return out

    def stats(self):
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "hit_tokens": self.hit_tokens,
                    "evictions": self.evictions,
                    "inserted_blocks": self.inserted_blocks,
                    "nodes": self._nodes,
                    "cached_tokens": self._nodes * self.block_size,
                    "capacity_tokens": self.capacity_tokens}

    # -- mutations ------------------------------------------------------------
    def insert(self, prompt_ids, blocks):
        """Register a just-prefilled request's full prompt blocks.

        ``blocks`` is the request's block list in logical order
        (``blocks[i]`` physically holds tokens ``[i*bs, (i+1)*bs)``);
        chunks already cached are skipped (their physical block is the
        one the request adopted at lookup), new chunks pin their block
        with a fresh cache-owned reference.  Ends by LRU-evicting down
        to the token budget."""
        with self._lock:
            node = self._root
            cap = self._match_cap(prompt_ids)
            for i, chunk in enumerate(self._chunks(prompt_ids, cap)):
                nxt = node.children.get(chunk)
                if nxt is None:
                    if i >= len(blocks):
                        raise MXNetError(
                            "block list shorter than the prompt's full "
                            "blocks")
                    self.allocator.share([blocks[i]])
                    nxt = _Node(chunk, blocks[i], next(self._tick),
                                node)
                    node.children[chunk] = nxt
                    self._nodes += 1
                    self.inserted_blocks += 1
                else:
                    nxt.tick = next(self._tick)
                node = nxt
            self._evict_to_budget()

    def _evict_to_budget(self):
        while self._nodes * self.block_size > self.capacity_tokens:
            leaf = None
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                if node.children:
                    stack.extend(node.children.values())
                elif leaf is None or node.tick < leaf.tick:
                    leaf = node
            if leaf is None:
                break
            del leaf.parent.children[leaf.chunk]
            self.allocator.release([leaf.block])
            self._nodes -= 1
            self.evictions += 1

    def clear(self):
        """Drop every cached prefix (releases all cache-held refs)."""
        with self._lock:
            stack = list(self._root.children.values())
            self._root.children = {}
            while stack:
                node = stack.pop()
                self.allocator.release([node.block])
                self.evictions += 1
                stack.extend(node.children.values())
            self._nodes = 0
