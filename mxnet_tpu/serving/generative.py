"""Continuous-batching decode engine for the llama generative path.

The engine owns one static-shape KV cache per layer —
``(num_slots, Hkv, max_len, head_dim)`` — and exactly THREE compiled
program families, all shape-stable under arbitrary request traffic:

* **step** — ``LlamaDecoder._step_slots_impl`` over all slots at once,
  every slot at its OWN position (vector ``pos``): one signature, ever.
  Vacant slots decode garbage at row 0 of their own slot; nobody reads
  it.
* **prefill** — the decoder's batched prompt pass at one
  (admit_bucket, prompt_bucket) shape per bucket pair, with per-row
  true lengths (vector ``t0``), returning each admitted prompt's first
  token and its full-length cache rows.
* **scatter** — writes the prefilled rows into the admitted slot
  indices of the live cache.  Vacant rows carry slot index
  ``num_slots``: out-of-bounds scatter indices DROP in XLA, so padding
  never touches a live slot.

Between any two step calls the scheduler may admit new requests
(prefill + scatter) or evict finished ones — the continuous-batching
join point.  Weights are frozen at engine build; ``int8=True`` stores
them as per-output-channel symmetric int8 (scale = max|row|/127) and
dequantizes in-kernel — the weight-only quantization the int8 MXU
pricing in ``INT8_TOPOLOGY_r05.json`` motivates.

The scheduler half (:class:`GenerativeScheduler`) runs the admit/step/
evict loop on one background thread, with the same queue, telemetry
and backpressure contract as the stateless :class:`~.scheduler.
BatchScheduler`.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import telemetry
from .bucketing import BucketPolicy, pad_batch
from .kv_cache import KVCacheManager
from .protocol import ServerClosedError
from .scheduler import _materialize

__all__ = ["LlamaServingEngine", "GenerativeScheduler"]

#: matmul weights that the int8 option quantizes (per-output-channel);
#: embeddings and the RMSNorm scales stay in the load dtype
_QUANT_KEYS = ("q", "k", "v", "o", "gate", "up", "down")
_LAYER_KEYS = ("ln_in", "q", "k", "v", "o", "ln_post", "gate", "up",
               "down")


def _quantize_mat(m):
    """Per-output-channel symmetric int8: rows of the (out, in) weight
    each get scale = max|row| / 127."""
    import jax.numpy as jnp

    m32 = m.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(m32), axis=1, keepdims=True)
                        / 127.0, 1e-8)
    q8 = jnp.clip(jnp.round(m32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q8, "scale": scale.astype(jnp.float32)}


def _quantize_tree(w):
    layers = []
    for L in w["layers"]:
        layers.append({k: _quantize_mat(L[k]) if k in _QUANT_KEYS
                       else L[k] for k in _LAYER_KEYS})
    return dict(layers=layers, emb=w["emb"], norm=w["norm"],
                head=_quantize_mat(w["head"]))


def _dequantize_tree(w):
    """Inverse of ``_quantize_tree`` inside the jit: int8 → f32 rows ×
    scales at trace time, so XLA sees ordinary dense matmuls (and on
    int8-capable MXUs can fuse the dequant into the gemm)."""
    def dq(leaf):
        if isinstance(leaf, dict):
            return leaf["q8"].astype(leaf["scale"].dtype) * leaf["scale"]
        return leaf

    layers = []
    for L in w["layers"]:
        layers.append({k: dq(L[k]) for k in _LAYER_KEYS})
    return dict(layers=layers, emb=w["emb"], norm=w["norm"],
                head=dq(w["head"]))


class LlamaServingEngine:
    """Device-side half of continuous batching for a LlamaForCausalLM."""

    def __init__(self, net, max_len=None, num_slots=4, int8=False):
        import jax
        import jax.numpy as jnp
        from ..models.llama import LlamaDecoder

        self.max_len = int(max_len or net.config.max_seq_len)
        self.num_slots = int(num_slots)
        self.int8 = bool(int8)
        dec = LlamaDecoder(net, self.max_len)
        self._dec = dec
        w = dec._weights()
        self._w = _quantize_tree(w) if self.int8 else w
        deq = _dequantize_tree if self.int8 else (lambda t: t)
        cfg = net.config
        shape = (self.num_slots, cfg.num_kv_heads, self.max_len,
                 cfg.head_dim)
        dt = w["emb"].dtype
        self._caches = [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                        for _ in range(cfg.num_layers)]
        # host mirrors: last emitted token + next write position per slot
        self._last = np.zeros(self.num_slots, np.int32)
        self._pos = np.zeros(self.num_slots, np.int32)
        self.steps = 0
        self._signatures = set()

        def _step_fn(wq, caches, ids, pos):
            logits, caches = dec._step_slots_impl(deq(wq), caches, ids,
                                                  pos)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), caches

        def _prefill_fn(wq, ids, t0):
            caches, logits = dec._prefill_impl(deq(wq), ids, t0)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), caches

        def _scatter_fn(caches, rows, slots):
            return [(kc.at[slots].set(nk), vc.at[slots].set(nv))
                    for (kc, vc), (nk, nv) in zip(caches, rows)]

        self._step = jax.jit(_step_fn, donate_argnums=(1,))
        self._prefill = jax.jit(_prefill_fn)
        self._scatter = jax.jit(_scatter_fn, donate_argnums=(0,))

    # -- observability --------------------------------------------------------
    def _note(self, key):
        if key not in self._signatures:
            self._signatures.add(key)
            telemetry.count("serving.engine_compile")

    def compiled_signatures(self):
        """Every (program, *bucket) shape this engine has compiled."""
        return sorted(self._signatures)

    # -- transitions ----------------------------------------------------------
    def admit(self, prompts_pad, t0s, slots):
        """Prefill ``prompts_pad`` (kb, lp) with true lengths ``t0s``
        (kb,) and scatter the resulting cache rows into ``slots`` (kb,)
        — vacant padding rows carry slot index ``num_slots`` and are
        dropped by XLA's out-of-bounds scatter rule.  Returns each
        row's first generated token (kb,) on host."""
        import jax.numpy as jnp

        kb, lp = prompts_pad.shape
        self._note(("prefill", kb, lp))
        toks, rows = self._prefill(self._w, jnp.asarray(prompts_pad),
                                   jnp.asarray(t0s, jnp.int32))
        caches = self._caches
        caches = self._scatter(caches, rows, jnp.asarray(slots, jnp.int32))
        self._caches = caches
        first = _materialize([toks])[0]
        for i, s in enumerate(slots):
            if s < self.num_slots:
                self._last[s] = first[i]
                self._pos[s] = t0s[i]
        return first

    def step(self, active):
        """One decode step over ALL slots; returns the (num_slots,)
        next-token vector on host and advances the ``active`` slots'
        mirrors.  Vacant slots run at pos 0 with token 0 — their output
        is never read and their garbage K/V write stays in their own
        slot row."""
        import jax.numpy as jnp

        self._note(("step",))
        caches = self._caches
        toks, caches = self._step(self._w, caches,
                                  jnp.asarray(self._last),
                                  jnp.asarray(self._pos))
        self._caches = caches
        self.steps += 1
        out = _materialize([toks])[0]
        for s in active:
            self._last[s] = out[s]
            self._pos[s] += 1
        return out

    def clear_slot(self, slot):
        self._last[slot] = 0
        self._pos[slot] = 0


class GenerativeScheduler:
    """Admit/step/evict loop: continuous batching over the engine.

    Requests carry ``prompt_ids`` + ``max_new_tokens``.  Admission
    happens between decode steps whenever slots are free — a late
    request joins the in-flight batch without stopping anyone else's
    decode (its ``joined_step``/``done_step`` land in the request
    record, which is how the tier-1 late-join test proves it).
    """

    def __init__(self, engine, queue, policy=None, summary_every=16,
                 poll_s=0.02):
        self.engine = engine
        self.queue = queue
        self.policy = policy or BucketPolicy(
            max_batch=engine.num_slots, max_length=engine.max_len,
            min_batch=1, min_length=8)
        self.mgr = KVCacheManager(engine.num_slots, engine.max_len)
        self.summary_every = int(summary_every)
        self.poll_s = float(poll_s)
        self.completed = 0
        self.failed = 0
        self.batches = 0
        self._seqs = {}       # slot -> (request, [generated tokens])
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="mxt-serving-decode",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain=True):
        self._stop.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if drain:
            while self._seqs or len(self.queue):
                self._admit_pending()
                if not self._seqs:
                    break
                self._decode_step()
        for r in self.queue.take_group(lambda r: 0, 1 << 30):
            r.future.set_exception(
                ServerClosedError("server stopped before execution"))

    # -- the loop -------------------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            admitted = self._admit_pending()
            if self._seqs:
                self._decode_step()
            elif not admitted:
                self.queue.wait_for_item(self.poll_s)

    def _prompt_bucket(self, req):
        return self.policy.length_bucket(len(req.prompt_ids))

    def _admit_pending(self):
        """Admit queued requests into free slots (one prompt-length
        bucket group per call, the FIFO head's)."""
        free = self.mgr.free_slots()
        if not free or not len(self.queue):
            return False
        group = self.queue.take_group(
            self._prompt_bucket, min(free, self.policy.max_batch))
        if not group:
            return False
        t_start = time.perf_counter()
        lb = self._prompt_bucket(group[0])
        kb = self.policy.batch_bucket(len(group))
        try:
            prompts = pad_batch([np.asarray(r.prompt_ids, np.int32)
                                 for r in group], kb, lb)
            t0s = np.full(kb, len(group[0].prompt_ids), np.int32)
            slots = np.full(kb, self.engine.num_slots, np.int32)
            for i, r in enumerate(group):
                t0s[i] = len(r.prompt_ids)
                slot = self.mgr.admit(r.id, t0s[i], r.max_new_tokens,
                                      step=self.engine.steps)
                slots[i] = slot
                r.slot = int(slot)
                r.joined_step = self.engine.steps
                r.t_start = t_start
                r.bucket = (kb, lb)
                r.batch_size = len(group)
            first = self.engine.admit(prompts, t0s, slots)
        except Exception as exc:
            for r in group:
                if r.slot is not None and r.slot in self.mgr._active:
                    self.mgr.evict(r.slot)
                r.future.set_exception(exc)
            self.failed += len(group)
            telemetry.count("serving.failed", len(group))
            return False
        t_first = time.perf_counter()
        for i, r in enumerate(group):
            r.t_first = t_first
            self._seqs[r.slot] = (r, [int(first[i])])
            if self.mgr.consume(r.slot):
                self._finish(r.slot)
        telemetry.count("serving.admitted", len(group))
        return True

    def _decode_step(self):
        active = self.mgr.active_slots()
        try:
            toks = self.engine.step(active)
        except Exception as exc:
            for slot in list(active):
                req, _ = self._seqs.pop(slot)
                self.mgr.evict(slot)
                self.engine.clear_slot(slot)
                req.future.set_exception(exc)
            self.failed += len(active)
            telemetry.count("serving.failed", len(active))
            return
        self.batches += 1
        telemetry.hist("serving.batch_size", len(active))
        for slot in active:
            self.mgr.advance(slot)   # the step wrote K/V at slot's pos
            _, tokens = self._seqs[slot]
            tokens.append(int(toks[slot]))
            if self.mgr.consume(slot):
                self._finish(slot)

    def _finish(self, slot):
        req, tokens = self._seqs.pop(slot)
        self.mgr.evict(slot)
        self.engine.clear_slot(slot)
        req.t_done = time.perf_counter()
        req.done_step = self.engine.steps
        n = req.max_new_tokens
        req.future.set_result(np.concatenate(
            [np.asarray(req.prompt_ids, np.int32),
             np.asarray(tokens[:n], np.int32)]))
        self._account(req)

    def _account(self, req):
        self.completed += 1
        telemetry.count("serving.completed")
        rec = req.record()
        if rec["queue_wait_ms"] is not None:
            telemetry.hist("serving.queue_wait_ms", rec["queue_wait_ms"])
        if rec["total_ms"] is not None:
            telemetry.hist("serving.total_ms", rec["total_ms"])
        if rec.get("ttft_ms") is not None:
            telemetry.hist("serving.ttft_ms", rec["ttft_ms"])
        telemetry.emit(rec)
        if self.summary_every and self.completed % self.summary_every == 0:
            self.emit_summary()

    def emit_summary(self):
        telemetry.emit({
            "record": "serving.latency",
            "completed": self.completed,
            "failed": self.failed,
            "batches": self.batches,
            "rejected": self.queue.rejected,
            "queue_wait_ms": telemetry.hist_summary("serving.queue_wait_ms"),
            "total_ms": telemetry.hist_summary("serving.total_ms"),
            "ttft_ms": telemetry.hist_summary("serving.ttft_ms"),
            "batch_size": telemetry.hist_summary("serving.batch_size"),
            "kv_cache": self.mgr.stats(),
        })
