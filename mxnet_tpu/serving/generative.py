"""Continuous-batching decode engine for the llama generative path.

The engine owns the device half of serving: weights (optionally int8),
the KV storage, and a fixed family of compiled programs that stay
shape-stable under arbitrary request traffic.  Two storage modes share
one surface (``kv_mode=``):

* **paged** (default since r11) — K/V lives in a shared block pool per
  layer, ``(num_blocks, Hkv, block_size, head_dim)``; each slot carries
  a block-table row (vacant entries = ``num_blocks``, the out-of-bounds
  sentinel XLA's scatter rule DROPS).  Capacity is bounded by tokens in
  flight, not ``max_len × num_slots``.  Programs: **step**
  (``LlamaDecoder._step_blocks_impl`` — one signature, ever),
  **prefill** (``_prefill_rows_impl`` at one (admit_bucket,
  prompt_bucket) shape per bucket pair, returning RAW K/V rows — no
  max_len allocation), and **scatter** (pad rows to block chunks and
  write them at the admitted physical block ids — the prefill→decode KV
  handoff).
* **slots** — the r8 ledger layout, one ``(num_slots, Hkv, max_len,
  head_dim)`` cache per layer, kept behind the pool for A/B
  (``ServerConfig(kv_mode="slots")``) and the legacy single-loop
  scheduler.

With ``mesh=`` the engine is mesh-native: every weight (and the KV
pool) is committed to the mesh via the serving partition-rule table
(``parallel.partition.SERVING_RULES`` unless ``partition_rules=``
overrides) — q/k/v/gate/up column-parallel, o/down row-parallel, KV
head axis sharded over ``tp`` — so the step/prefill/scatter compiles
are keyed by the mesh their inputs live on: one decode compile per
engine lifetime per mesh.  A dp axis is NOT this engine's business:
the server splits a dp×tp mesh into per-replica tp submeshes and runs
one engine per replica (serving/lanes.py).

Thread discipline: the prefill lane and the decode lane share one
engine.  ``dev_lock`` serializes every dispatch that MUTATES the KV
storage (decode step, handoff scatter, slot clears); the prefill
forward itself runs outside the lock, so a long prompt never stalls
decode — only its cheap block scatter briefly takes the lock.

Between any two step calls the scheduler may admit new requests
(prefill + scatter) or evict finished ones — the continuous-batching
join point.  Weights are frozen at engine build; ``int8=True`` stores
them as per-output-channel symmetric int8 (scale = max|row|/127) and
dequantizes in-kernel — the weight-only quantization the int8 MXU
pricing in ``INT8_TOPOLOGY_r05.json`` motivates.

The scheduler half (:class:`GenerativeScheduler`) runs the legacy
single-thread admit/step/evict loop for the slots mode; the paged path
is driven by the disaggregated lanes in :mod:`.lanes`.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import telemetry
from ..telemetry import numerics as _numerics
from ..telemetry import retrace as _retrace
from ..telemetry import tracing
from ..base import MXNetError
from .bucketing import BucketPolicy, pad_batch
from .kv_cache import KVCacheManager
from .protocol import ServerClosedError
from .scheduler import _materialize

__all__ = ["LlamaServingEngine", "GenerativeScheduler"]

#: reviewed signature budget (mxlint T15): one decode-step program per
#: (batch bucket, cache length bucket) plus one prefill program per
#: prompt bucket — the bucket tables are fixed at engine construction
__compile_signatures__ = {
    "serving_step": "1 per (batch bucket, cache bucket); prefill adds "
                    "1 per prompt bucket",
    "serving_verify": "1 per engine — the k-token speculative verify "
                      "window (num_slots, spec_k+1) is shape-static",
    "serving_gather": "1 per (batch bucket, prefix bucket) — dense "
                      "prefix copy for suffix prefill",
    "serving_prefill_sfx": "1 per (batch bucket, prefix bucket, suffix "
                           "bucket) — radix-hit suffix prefill",
}

#: matmul weights that the int8 option quantizes (per-output-channel);
#: embeddings and the RMSNorm scales stay in the load dtype
_QUANT_KEYS = ("q", "k", "v", "o", "gate", "up", "down")
_LAYER_KEYS = ("ln_in", "q", "k", "v", "o", "ln_post", "gate", "up",
               "down")


def _quantize_mat(m):
    """Per-output-channel symmetric int8: rows of the (out, in) weight
    each get scale = max|row| / 127."""
    import jax.numpy as jnp

    m32 = m.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(m32), axis=1, keepdims=True)
                        / 127.0, 1e-8)
    q8 = jnp.clip(jnp.round(m32 / scale), -127, 127).astype(jnp.int8)
    return {"q8": q8, "scale": scale.astype(jnp.float32)}


def _quantize_tree(w):
    layers = []
    for L in w["layers"]:
        layers.append({k: _quantize_mat(L[k]) if k in _QUANT_KEYS
                       else L[k] for k in _LAYER_KEYS})
    return dict(layers=layers, emb=w["emb"], norm=w["norm"],
                head=_quantize_mat(w["head"]))


def _dequantize_tree(w):
    """Inverse of ``_quantize_tree`` inside the jit: int8 → f32 rows ×
    scales at trace time, so XLA sees ordinary dense matmuls (and on
    int8-capable MXUs can fuse the dequant into the gemm)."""
    def dq(leaf):
        if isinstance(leaf, dict):
            return leaf["q8"].astype(leaf["scale"].dtype) * leaf["scale"]
        return leaf

    layers = []
    for L in w["layers"]:
        layers.append({k: dq(L[k]) for k in _LAYER_KEYS})
    return dict(layers=layers, emb=w["emb"], norm=w["norm"],
                head=dq(w["head"]))


def _named_weight_items(w):
    """(rule-matchable name, getter/setter path) for every leaf of the
    decoder weight tree — the serving-side analog of Gluon's dotted
    parameter paths, so ``SERVING_RULES``/``LLAMA_RULES`` patterns match
    unchanged (``layers.0.q_weight`` hits the column-parallel rule the
    same way ``...self_attn.q_proj.weight`` does at training time)."""
    items = []
    for i, L in enumerate(w["layers"]):
        for key in L:
            items.append((f"layers.{i}.{key}_weight", ("layers", i, key)))
    items.append(("embed_weight", ("emb",)))
    items.append(("norm_weight", ("norm",)))
    items.append(("lm_head_weight", ("head",)))
    return items


class LlamaServingEngine:
    """Device-side half of continuous batching for a LlamaForCausalLM."""

    def __init__(self, net, max_len=None, num_slots=4, int8=False,
                 kv_mode="slots", block_size=16, num_blocks=None,
                 mesh=None, partition_rules=None, replica_id=0,
                 spec_k=0):
        import jax
        import jax.numpy as jnp
        from ..models.llama import LlamaDecoder

        if kv_mode not in ("paged", "slots"):
            raise MXNetError(f"unknown kv_mode {kv_mode!r}; "
                             "expected 'paged' or 'slots'")
        self.spec_k = int(spec_k)
        if self.spec_k and kv_mode != "paged":
            raise MXNetError("speculative verify (spec_k > 0) requires "
                             "kv_mode='paged'")
        self.max_len = int(max_len or net.config.max_seq_len)
        self.num_slots = int(num_slots)
        self.int8 = bool(int8)
        self.kv_mode = kv_mode
        self.mesh = mesh
        self.partition_rules = partition_rules
        self.replica_id = int(replica_id)
        self.dev_lock = threading.RLock()
        dec = LlamaDecoder(net, self.max_len)
        self._dec = dec
        w = dec._weights()
        self._w = _quantize_tree(w) if self.int8 else w
        deq = _dequantize_tree if self.int8 else (lambda t: t)
        cfg = net.config
        dt = w["emb"].dtype
        if kv_mode == "paged":
            self.block_size = int(block_size)
            if self.block_size < 1:
                raise MXNetError("block_size must be >= 1")
            #: static block-table width — the step gathers this many
            #: blocks per slot regardless of actual ownership
            self.max_blocks = -(-self.max_len // self.block_size)
            self.num_blocks = int(num_blocks or
                                  self.num_slots * self.max_blocks)
            pshape = (self.num_blocks, cfg.num_kv_heads, self.block_size,
                      cfg.head_dim)
            self._pool = [(jnp.zeros(pshape, dt), jnp.zeros(pshape, dt))
                          for _ in range(cfg.num_layers)]
            self._tables = np.full((self.num_slots, self.max_blocks),
                                   self.num_blocks, np.int32)
            self._caches = None
        else:
            self.block_size = self.num_blocks = self.max_blocks = None
            shape = (self.num_slots, cfg.num_kv_heads, self.max_len,
                     cfg.head_dim)
            self._caches = [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                            for _ in range(cfg.num_layers)]
            self._pool = self._tables = None
        with self.dev_lock:
            # uncontended at construction; taken so the placement
            # writes to _w/_pool/_caches share the KV mutators' guard
            self._place_on_mesh_locked()
        # host mirrors: last emitted token + next write position per slot
        self._last = np.zeros(self.num_slots, np.int32)
        self._pos = np.zeros(self.num_slots, np.int32)
        self.steps = 0
        self._signatures = set()

        # decode-step logit stats behind the same gate as the training
        # tiers — baked at engine construction, so the jitted step keeps
        # one signature per numerics mode (rebuild the engine to toggle)
        self._numerics = _numerics.trace_enabled()
        numerics_on = self._numerics
        if kv_mode == "paged":

            def _step_fn(wq, pools, tables, ids, pos):
                logits, pools = dec._step_blocks_impl(deq(wq), pools,
                                                      tables, ids, pos)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if numerics_on:
                    return tok, pools, _numerics.stats_of(logits)
                return tok, pools

            def _prefill_fn(wq, ids, t0):
                rows, logits = dec._prefill_rows_impl(deq(wq), ids, t0)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), rows

            def _verify_fn(wq, pools, tables, toks, pos0):
                logits, pools = dec._verify_blocks_impl(
                    deq(wq), pools, tables, toks, pos0)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if numerics_on:
                    return tok, pools, _numerics.stats_of(logits)
                return tok, pools

            nb_total = self.num_blocks

            def _gather_fn(pools, rows_idx):
                # rows_idx (KB, NBP) int32 physical block ids in logical
                # order, sentinel-padded — dense per-row prefix K/V
                # copies (KB, Hkv, NBP*bs, hd) for the suffix prefill;
                # sentinel entries clamp to garbage rows the suffix
                # mask (t < s0) never exposes
                kb_, nbp_ = rows_idx.shape
                g = jnp.minimum(rows_idx, nb_total - 1)
                out = []
                for kp, vp in pools:
                    out.append((
                        kp[g].transpose(0, 2, 1, 3, 4)
                        .reshape(kb_, kp.shape[1], nbp_ * self.block_size,
                                 kp.shape[3]),
                        vp[g].transpose(0, 2, 1, 3, 4)
                        .reshape(kb_, vp.shape[1], nbp_ * self.block_size,
                                 vp.shape[3])))
                return out

            def _prefill_sfx_fn(wq, pre_kv, ids, t0, s0):
                rows, logits = dec._prefill_suffix_impl(
                    deq(wq), pre_kv, ids, t0, s0)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                    rows

            bs = self.block_size

            def _scatter_fn(pools, rows, flat_idx):
                # rows[l]: (KB, Hkv, Lp, hd) raw prefill K/V; chunk each
                # row into ceil(Lp/bs) block-sized pieces and write them
                # at flat_idx (KB*nbp,) physical block ids — sentinel
                # ids (== num_blocks) drop, covering vacant batch rows
                # AND chunks past a short prompt's allocation
                out = []
                for (kp, vp), (k, v) in zip(pools, rows):
                    kb, hkv, lp, hd = k.shape
                    nbp = flat_idx.shape[0] // kb
                    pad = ((0, 0), (0, 0), (0, nbp * bs - lp), (0, 0))

                    def chunk(a):
                        return jnp.pad(a, pad) \
                            .reshape(kb, hkv, nbp, bs, hd) \
                            .transpose(0, 2, 1, 3, 4) \
                            .reshape(kb * nbp, hkv, bs, hd)

                    out.append((kp.at[flat_idx].set(chunk(k), mode="drop"),
                                vp.at[flat_idx].set(chunk(v), mode="drop")))
                return out

        else:

            def _step_fn(wq, caches, ids, pos):
                logits, caches = dec._step_slots_impl(deq(wq), caches,
                                                      ids, pos)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if numerics_on:
                    return tok, caches, _numerics.stats_of(logits)
                return tok, caches

            def _prefill_fn(wq, ids, t0):
                caches, logits = dec._prefill_impl(deq(wq), ids, t0)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                    caches

            def _scatter_fn(caches, rows, slots):
                return [(kc.at[slots].set(nk), vc.at[slots].set(nv))
                        for (kc, vc), (nk, nv) in zip(caches, rows)]

        self._step = jax.jit(_step_fn, donate_argnums=(1,))
        self._prefill = jax.jit(_prefill_fn)
        self._scatter = jax.jit(_scatter_fn, donate_argnums=(0,))
        if kv_mode == "paged":
            self._verify = jax.jit(_verify_fn, donate_argnums=(1,))
            self._gather = jax.jit(_gather_fn)
            self._prefill_sfx = jax.jit(_prefill_sfx_fn)
        else:
            self._verify = self._gather = self._prefill_sfx = None

    # -- mesh placement -------------------------------------------------------
    def _place_on_mesh_locked(self):
        """Commit weights + KV storage to ``self.mesh`` per the serving
        rule table: every leaf gets an explicit NamedSharding (sharded
        or replicated), so jit infers the device assignment from its
        inputs and the compiles are mesh-keyed.  int8 leaves shard the
        q8 rows like the original weight; the per-row scales follow the
        output dim.  Caller holds ``dev_lock``."""
        if self.mesh is None:
            return
        import jax

        from ..parallel import _named_sharding, _pspec
        from ..parallel.partition import as_rules

        rules = as_rules(self.partition_rules
                         if self.partition_rules is not None
                         else "llama_serving")
        mesh = self.mesh
        self._replicated = _named_sharding(mesh, _pspec())

        def put(leaf, spec):
            return jax.device_put(leaf, _named_sharding(mesh,
                                                        _pspec(*spec)))

        def leaf_shape(leaf):
            return leaf["q8"].shape if isinstance(leaf, dict) \
                else leaf.shape

        items = _named_weight_items(self._w)
        shapes = {}
        tree = {"layers": [dict(L) for L in self._w["layers"]],
                "emb": self._w["emb"], "norm": self._w["norm"],
                "head": self._w["head"]}
        for name, path in items:
            leaf = tree["layers"][path[1]][path[2]] if len(path) == 3 \
                else tree[path[0]]
            shapes[name] = leaf_shape(leaf)
        kv = self._pool if self.kv_mode == "paged" else self._caches
        for i in range(len(kv)):
            shapes[f"layers.{i}.kv_pool"] = kv[i][0].shape
        specs = rules.specs(shapes, mesh)
        for name, path in items:
            spec = specs.get(name, ())
            if len(path) == 3:
                leaf = tree["layers"][path[1]][path[2]]
            else:
                leaf = tree[path[0]]
            if isinstance(leaf, dict):
                placed = {"q8": put(leaf["q8"], spec),
                          "scale": put(leaf["scale"],
                                       ((spec[0] if spec else None),
                                        None))}
            else:
                placed = put(leaf, spec)
            if len(path) == 3:
                tree["layers"][path[1]][path[2]] = placed
            else:
                tree[path[0]] = placed
        self._w = tree
        placed_kv = []
        for i, (kb, vb) in enumerate(kv):
            spec = specs.get(f"layers.{i}.kv_pool", ())
            placed_kv.append((put(kb, spec), put(vb, spec)))
        if self.kv_mode == "paged":
            self._pool = placed_kv
        else:
            self._caches = placed_kv

    def _dev(self, a, dtype=np.int32):
        """Host array → device, committed to the engine's mesh when
        sharded (replicas may live entirely off the default device)."""
        import jax
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(a, dtype)
        return jax.device_put(np.asarray(a, dtype), self._replicated)

    # -- observability --------------------------------------------------------
    def _note(self, key):
        if key not in self._signatures:
            self._signatures.add(key)
            telemetry.count("serving.engine_compile")
            if _retrace._enabled:
                # registered compile site, one per program (prefill keys
                # per bucket; a post-warmup unwarmed bucket is a retrace)
                if len(key) == 4:
                    comps = {"batch": key[1], "prefix_len": key[2],
                             "suffix_len": key[3]}
                elif len(key) == 3:
                    comps = {"batch": key[1], "prompt_len": key[2]}
                else:
                    comps = {"program": key[0]}
                _retrace.observe(
                    "serving_" + str(key[0]), id(self), comps,
                    site="mxnet_tpu.serving.generative:"
                         "LlamaServingEngine (%s)" % (key[0],))

    def compiled_signatures(self):
        """Every (program, *bucket) shape this engine has compiled."""
        return sorted(self._signatures)

    def kv_pool_bytes(self):
        """PER-DEVICE bytes of the KV storage (pool or slot caches),
        summed over layers and both of K/V — the figure the memory
        planner's ``plan_kv_pool`` predicts pre-build.  On a tp mesh
        each device holds one shard of the pool's head axis, so this is
        the single-shard footprint, not the global array size."""
        def shard_bytes(a):
            shards = getattr(a, "addressable_shards", None)
            if shards:
                return shards[0].data.nbytes
            return a.nbytes

        with self.dev_lock:
            kv = self._pool if self.kv_mode == "paged" else self._caches
            return int(sum(shard_bytes(k) + shard_bytes(v)
                           for k, v in kv))

    # -- transitions (slots mode: legacy single-loop scheduler) ---------------
    def admit(self, prompts_pad, t0s, slots):
        """Prefill ``prompts_pad`` (kb, lp) with true lengths ``t0s``
        (kb,) and scatter the resulting cache rows into ``slots`` (kb,)
        — vacant padding rows carry slot index ``num_slots`` and are
        dropped by XLA's out-of-bounds scatter rule.  Returns each
        row's first generated token (kb,) on host."""
        if self.kv_mode != "slots":
            raise MXNetError("admit() is the slot-ledger path; the paged "
                             "engine admits via prefill_rows/commit_rows")
        kb, lp = prompts_pad.shape
        self._note(("prefill", kb, lp))
        toks, rows = self._prefill(self._w, self._dev(prompts_pad),
                                   self._dev(t0s))
        with self.dev_lock:
            self._caches = self._scatter(self._caches, rows,
                                         self._dev(slots))
        first = _materialize([toks])[0]
        with self.dev_lock:
            for i, s in enumerate(slots):
                if s < self.num_slots:
                    self._last[s] = first[i]
                    self._pos[s] = t0s[i]
        return first

    # -- transitions (paged mode: disaggregated lanes) ------------------------
    def prefill_rows(self, prompts_pad, t0s):
        """Prefill lane, phase 1: the heavy prompt forward.  Runs
        WITHOUT the device lock — decode steps interleave freely while
        a long prompt prefills.  Returns (first-token device array,
        per-layer raw K/V rows) for :meth:`commit_rows`."""
        if self.kv_mode != "paged":
            raise MXNetError("prefill_rows() requires kv_mode='paged'")
        kb, lp = prompts_pad.shape
        self._note(("prefill", kb, lp))
        return self._prefill(self._w, self._dev(prompts_pad),
                             self._dev(t0s))

    def commit_rows(self, rows, slots, block_lists, t0s, first,
                    skip_blocks=None):
        """Prefill lane, phase 2: the KV handoff.  Under the device
        lock (briefly — one scatter dispatch), write the prefilled rows
        into each admitted request's blocks and install the block
        tables + decode mirrors, after which the decode lane's next
        step adopts the slots.  ``first`` is the already-materialized
        first-token vector (kb,); vacant rows carry slot id
        ``num_slots`` and sentinel blocks.

        ``skip_blocks`` (r19 radix path): per-row count of leading
        SHARED prefix blocks already holding K/V — ``rows`` then only
        carry the novel suffix, the scatter targets the block list past
        the shared prefix, and ``t0s`` stays the FULL prompt length
        (the decode cursor).  Shared blocks are never written."""
        import jax.numpy as jnp

        kb = len(slots)
        lp = rows[0][0].shape[2]
        nbp = -(-lp // self.block_size)
        flat = np.full(kb * nbp, self.num_blocks, np.int32)
        for r, blocks in enumerate(block_lists):
            if blocks is None:
                continue
            skip = 0 if skip_blocks is None else int(skip_blocks[r])
            tail = blocks[skip:]
            take = min(nbp, len(tail))
            flat[r * nbp: r * nbp + take] = tail[:take]
        with self.dev_lock:
            self._pool = self._scatter(self._pool, rows, self._dev(flat))
            for i, s in enumerate(slots):
                if s < self.num_slots:
                    row = np.full(self.max_blocks, self.num_blocks,
                                  np.int32)
                    blocks = block_lists[i]
                    row[:len(blocks)] = blocks
                    self._tables[s] = row
                    self._last[s] = first[i]
                    self._pos[s] = t0s[i]

    def gather_prefix(self, rows_idx):
        """Radix-hit prefill, phase 0: dense per-request copies of the
        shared prefix blocks' K/V, ``rows_idx`` (kb, nbp) physical ids
        sentinel-padded.  Dispatch runs UNDER the device lock — the
        decode step donates the pool buffer, so an unlocked read could
        alias a donated buffer mid-step; the returned copies are fresh
        arrays, safe to consume outside the lock."""
        if self.kv_mode != "paged":
            raise MXNetError("gather_prefix() requires kv_mode='paged'")
        kb, nbp = rows_idx.shape
        self._note(("gather", kb, nbp * self.block_size))
        with self.dev_lock:
            return self._gather(self._pool, self._dev(rows_idx))

    def prefill_suffix(self, prefix_kv, prompts_pad, t0s, s0s):
        """Radix-hit prefill, phase 1: the novel-suffix forward against
        the gathered prefix K/V.  Like :meth:`prefill_rows` this runs
        WITHOUT the device lock (``prefix_kv`` is a private copy).
        ``prompts_pad`` (kb, ls) carries only suffix tokens, ``t0s``
        their true suffix lengths, ``s0s`` each row's reused prefix
        length (block-aligned; 0 = no hit).  Returns (first-token
        device array, suffix K/V rows) for
        :meth:`commit_rows(..., skip_blocks=)`."""
        if self.kv_mode != "paged":
            raise MXNetError("prefill_suffix() requires kv_mode='paged'")
        kb, ls = prompts_pad.shape
        lpre = prefix_kv[0][0].shape[2]
        self._note(("prefill_sfx", kb, lpre, ls))
        return self._prefill_sfx(self._w, prefix_kv,
                                 self._dev(prompts_pad),
                                 self._dev(t0s), self._dev(s0s))

    # -- transitions (both modes) ---------------------------------------------
    def step(self, active):
        """One decode step over ALL slots; returns the (num_slots,)
        next-token vector on host and advances the ``active`` slots'
        mirrors.  Vacant slots run at pos 0 with token 0 — their output
        is never read, and their K/V write lands in their own slot row
        (slots mode) or is dropped at the sentinel block (paged).  The
        device lock covers dispatch and mirror updates, NOT the host
        materialization wait — handoff scatters interleave with the
        wait."""
        self._note(("step",))
        lstats = None
        with self.dev_lock:
            if self.kv_mode == "paged":
                if self._numerics:
                    toks, pool, lstats = self._step(
                        self._w, self._pool, self._dev(self._tables),
                        self._dev(self._last), self._dev(self._pos))
                else:
                    toks, pool = self._step(
                        self._w, self._pool, self._dev(self._tables),
                        self._dev(self._last), self._dev(self._pos))
                self._pool = pool
            else:
                if self._numerics:
                    toks, caches, lstats = self._step(
                        self._w, self._caches, self._dev(self._last),
                        self._dev(self._pos))
                else:
                    toks, caches = self._step(
                        self._w, self._caches, self._dev(self._last),
                        self._dev(self._pos))
                self._caches = caches
            self.steps += 1
        if lstats is not None:
            # queue the decode-step logit stats (device scalars) for the
            # stride harvest, outside the device lock
            _numerics.record_compiled(("serving.logits",), (lstats,))
        out = _materialize([toks])[0]
        with self.dev_lock:
            for s in active:
                self._last[s] = out[s]
                self._pos[s] += 1
        return out

    def verify(self, drafts):
        """Speculative decode: ONE multi-position target forward over
        the window ``[last_committed, draft_1..draft_k]`` per slot.
        ``drafts`` is (num_slots, k) int32 (vacant rows are ignored —
        their writes drop at the sentinel).  Returns the (num_slots,
        k+1) greedy verdict matrix on host: column j is the target's
        next token after consuming the window's first j+1 tokens.

        Unlike :meth:`step` the mirrors are NOT advanced here — the
        decode lane computes each slot's accepted length, rolls the
        manager back via ``truncate``, and commits the mirrors with
        :meth:`set_mirror`.  The window's K/V lands in the pool
        optimistically; rejected columns stay beyond the rolled-back
        cursor (masked) until the next window overwrites them."""
        if self._verify is None:
            raise MXNetError("verify() requires kv_mode='paged'")
        self._note(("verify",))
        lstats = None
        with self.dev_lock:
            toks_mat = np.concatenate(
                [self._last[:, None], np.asarray(drafts, np.int32)],
                axis=1)
            if self._numerics:
                out, pool, lstats = self._verify(
                    self._w, self._pool, self._dev(self._tables),
                    self._dev(toks_mat), self._dev(self._pos))
            else:
                out, pool = self._verify(
                    self._w, self._pool, self._dev(self._tables),
                    self._dev(toks_mat), self._dev(self._pos))
            self._pool = pool
            self.steps += 1
        if lstats is not None:
            _numerics.record_compiled(("serving.logits",), (lstats,))
        return _materialize([out])[0]

    def last_tokens(self):
        """Snapshot of the per-slot last-committed-token mirror."""
        with self.dev_lock:
            return self._last.copy()

    def positions(self):
        """Snapshot of the per-slot committed write cursors."""
        with self.dev_lock:
            return self._pos.copy()

    def set_mirror(self, slot, last, pos):
        """Commit a slot's decode mirror (speculative acceptance, or
        aligning a draft engine's cursor with the target's)."""
        with self.dev_lock:
            self._last[slot] = int(last)
            self._pos[slot] = int(pos)

    def clear_slot(self, slot):
        with self.dev_lock:
            self._last[slot] = 0
            self._pos[slot] = 0
            if self._tables is not None:
                self._tables[slot] = self.num_blocks


class GenerativeScheduler:
    """Admit/step/evict loop: continuous batching over the engine.

    This is the LEGACY single-thread loop for the slot-ledger mode
    (``ServerConfig(kv_mode="slots")``) — one thread interleaves
    admission (prefill+scatter) with decode steps.  The paged default
    runs the disaggregated prefill/decode lanes in :mod:`.lanes`
    instead.  Requests carry ``prompt_ids`` + ``max_new_tokens``.
    Admission happens between decode steps whenever slots are free — a
    late request joins the in-flight batch without stopping anyone
    else's decode (its ``joined_step``/``done_step`` land in the
    request record, which is how the tier-1 late-join test proves it).
    """

    def __init__(self, engine, queue, policy=None, summary_every=16,
                 poll_s=0.02, slo=None):
        if engine.kv_mode != "slots":
            raise MXNetError(
                "GenerativeScheduler drives the slot-ledger engine; "
                "paged engines are driven by serving.lanes")
        self.engine = engine
        self.queue = queue
        self.slo = slo   # shared SLOTracker (metrics.py) or None
        self.policy = policy or BucketPolicy(
            max_batch=engine.num_slots, max_length=engine.max_len,
            min_batch=1, min_length=8)
        self.mgr = KVCacheManager(engine.num_slots, engine.max_len)
        self.summary_every = int(summary_every)
        self.poll_s = float(poll_s)
        self.completed = 0
        self.failed = 0
        self.batches = 0
        self._seqs = {}       # slot -> (request, [generated tokens])
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="mxt-serving-decode",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain=True):
        self._stop.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if drain:
            while self._seqs or len(self.queue):
                self._admit_pending()
                if not self._seqs:
                    break
                self._decode_step()
        for r in self.queue.take_group(lambda r: 0, 1 << 30):
            r.future.set_exception(
                ServerClosedError("server stopped before execution"))

    # -- the loop -------------------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            admitted = self._admit_pending()
            if self._seqs:
                self._decode_step()
            elif not admitted:
                self.queue.wait_for_item(self.poll_s)

    def _prompt_bucket(self, req):
        return self.policy.length_bucket(len(req.prompt_ids))

    def _admit_pending(self):
        """Admit queued requests into free slots (one prompt-length
        bucket group per call, the FIFO head's)."""
        free = self.mgr.free_slots()
        if not free or not len(self.queue):
            return False
        group = self.queue.take_group(
            self._prompt_bucket, min(free, self.policy.max_batch))
        if not group:
            return False
        t_start = time.perf_counter()
        lb = self._prompt_bucket(group[0])
        kb = self.policy.batch_bucket(len(group))
        try:
            prompts = pad_batch([np.asarray(r.prompt_ids, np.int32)
                                 for r in group], kb, lb)
            t0s = np.full(kb, len(group[0].prompt_ids), np.int32)
            slots = np.full(kb, self.engine.num_slots, np.int32)
            for i, r in enumerate(group):
                t0s[i] = len(r.prompt_ids)
                slot = self.mgr.admit(r.id, t0s[i], r.max_new_tokens,
                                      step=self.engine.steps)
                slots[i] = slot
                r.slot = int(slot)
                r.replica = self.engine.replica_id
                r.joined_step = self.engine.steps
                r.t_start = t_start
                r.bucket = (kb, lb)
                r.batch_size = len(group)
            first = self.engine.admit(prompts, t0s, slots)
        except Exception as exc:
            for r in group:
                if r.slot is not None and r.slot in self.mgr._active:
                    self.mgr.evict(r.slot)
                r.replica = self.engine.replica_id
                r.future.set_exception(exc)
                self._fail(r, exc, lane="prefill")
            tracing.incident("replica_exception",
                             context={"replica": self.engine.replica_id,
                                      "lane": "prefill",
                                      "error": repr(exc)})
            return False
        t_first = time.perf_counter()
        mates = [r.id for r in group]
        for i, r in enumerate(group):
            r.t_first = t_first
            if r.trace is not None:
                r.trace.add("queue", r.t_submit, t_start,
                            replica=r.replica)
                r.trace.add("prefill", t_start, t_first,
                            replica=r.replica, slot=r.slot,
                            bucket=list(r.bucket),
                            mates=[m for m in mates if m != r.id])
            self._seqs[r.slot] = (r, [int(first[i])])
            if self.mgr.consume(r.slot):
                self._finish(r.slot)
        telemetry.count("serving.admitted", len(group))
        return True

    def _decode_step(self):
        active = self.mgr.active_slots()
        t0 = time.perf_counter()
        try:
            toks = self.engine.step(active)
        except Exception as exc:
            for slot in list(active):
                req, _ = self._seqs.pop(slot)
                self.mgr.evict(slot)
                self.engine.clear_slot(slot)
                req.future.set_exception(exc)
                self._fail(req, exc, lane="decode")
            tracing.incident("replica_exception",
                             context={"replica": self.engine.replica_id,
                                      "lane": "decode",
                                      "error": repr(exc)})
            return
        t1 = time.perf_counter()
        self.batches += 1
        telemetry.hist("serving.batch_size", len(active))
        step_idx = self.engine.steps
        for slot in active:
            self.mgr.advance(slot)   # the step wrote K/V at slot's pos
            req, tokens = self._seqs[slot]
            tokens.append(int(toks[slot]))
            if req.trace is not None:
                req.trace.add("decode.step", t0, t1, step=step_idx,
                              batch=len(active), replica=req.replica,
                              slot=slot)
            if self.mgr.consume(slot):
                self._finish(slot)

    def _finish(self, slot):
        req, tokens = self._seqs.pop(slot)
        self.mgr.evict(slot)
        self.engine.clear_slot(slot)
        req.t_done = time.perf_counter()
        req.done_step = self.engine.steps
        n = req.max_new_tokens
        req.future.set_result(np.concatenate(
            [np.asarray(req.prompt_ids, np.int32),
             np.asarray(tokens[:n], np.int32)]))
        self._account(req)

    def _account(self, req):
        self.completed += 1
        telemetry.count("serving.completed")
        telemetry.count(f"serving.completed|replica={req.replica}")
        rec = req.record(lane="decode")
        tag = f"|replica={req.replica}"
        if rec["queue_wait_ms"] is not None:
            telemetry.hist("serving.queue_wait_ms", rec["queue_wait_ms"])
            telemetry.hist("serving.queue_wait_ms" + tag,
                           rec["queue_wait_ms"])
        if rec["total_ms"] is not None:
            telemetry.hist("serving.total_ms", rec["total_ms"])
            telemetry.hist("serving.total_ms" + tag, rec["total_ms"])
        if rec.get("ttft_ms") is not None:
            telemetry.hist("serving.ttft_ms", rec["ttft_ms"])
            telemetry.hist("serving.ttft_ms" + tag, rec["ttft_ms"])
        if rec.get("tpot_ms") is not None:
            telemetry.hist("serving.tpot_ms", rec["tpot_ms"])
            telemetry.hist("serving.tpot_ms" + tag, rec["tpot_ms"])
        if self.slo is not None:
            rec["slo_met"] = self.slo.observe(
                tenant=req.tenant, ttft_ms=rec.get("ttft_ms"),
                tpot_ms=rec.get("tpot_ms"))
        telemetry.emit(rec)
        if req.trace is not None:
            req.trace.event("evict", replica=req.replica, slot=req.slot)
            tracing.finish(req.trace, status="ok", replica=req.replica,
                           lane="decode", request_id=req.id)
        if self.summary_every and self.completed % self.summary_every == 0:
            self.emit_summary()

    def _fail(self, req, exc, lane):
        """Failure-path twin of :meth:`_account`: error record with
        replica + lane, failed counters, trace seal."""
        self.failed += 1
        telemetry.count("serving.failed")
        telemetry.count(f"serving.failed|replica={req.replica}")
        req.t_done = time.perf_counter()
        telemetry.emit(req.record(lane=lane, status="error",
                                  error=repr(exc)))
        if req.trace is not None:
            tracing.finish(req.trace, status="error",
                           replica=req.replica, lane=lane,
                           error=repr(exc), request_id=req.id)

    def emit_summary(self):
        telemetry.emit({
            "record": "serving.latency",
            "completed": self.completed,
            "failed": self.failed,
            "batches": self.batches,
            "rejected": self.queue.rejected,
            "queue_wait_ms": telemetry.hist_summary("serving.queue_wait_ms"),
            "total_ms": telemetry.hist_summary("serving.total_ms"),
            "ttft_ms": telemetry.hist_summary("serving.ttft_ms"),
            "batch_size": telemetry.hist_summary("serving.batch_size"),
            "kv_cache": self.mgr.stats(),
        })
