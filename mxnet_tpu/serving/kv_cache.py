"""KV-cache capacity accounting for continuous-batching decode.

Two generations of the same host-side ledger live here:

* :class:`KVCacheManager` — the r8 **slot ledger**: one fixed
  ``max_len`` cache row per slot, capacity = ``num_slots × max_len``
  tokens whether or not a request ever uses its worst case.  Kept
  importable behind the paged pool for A/B (``ServerConfig(
  kv_mode="slots")``) and for the legacy single-loop scheduler.
* :class:`PagedKVCacheManager` — the r11 **paged pool**: device K/V
  lives in fixed-size blocks (``block_size`` tokens each) drawn from a
  shared :class:`BlockAllocator`; each request owns a *block list*
  sized to its actual ``prompt_len + max_new_tokens`` budget, so pool
  capacity is bounded by tokens in flight, not by
  ``max_len × num_slots``.  A long-prompt + short-prompt mix that the
  slot ledger could only host with worst-case reservations fits a much
  smaller pool (the r11 capacity acceptance test admits a mix whose
  slot-ledger worst case exceeds the pool outright).

Both managers expose the same transition surface (``admit`` /
``advance`` / ``consume`` / ``evict``) plus ``check()`` invariants and
``stats()`` with fragmentation and peak-token occupancy.  The paged
manager is touched by TWO lane threads (prefill admits, decode
advances/evicts — docs/serving.md) and serializes its transitions on an
internal lock; the slot ledger stays single-threaded under the legacy
scheduler.

Device-side block contents are the engine's problem: a freshly
allocated block may hold a previous tenant's K/V, but the per-slot
causal mask (``t <= pos``) hides every position the current request has
not yet written, so stale rows are unreachable — the same invariant
that lets the slot ledger skip zeroing slot rows.
"""
from __future__ import annotations

import threading

from ..base import MXNetError

__all__ = ["KVCacheManager", "PagedKVCacheManager", "BlockAllocator",
           "SlotState"]


class SlotState:
    """One occupied slot's bookkeeping."""

    __slots__ = ("request_id", "pos", "remaining", "joined_step",
                 "blocks", "reserved")

    def __init__(self, request_id, pos, remaining, joined_step,
                 blocks=None, reserved=0):
        self.request_id = request_id
        self.pos = pos              # next cache row the step writes
        self.remaining = remaining  # tokens still owed to the request
        self.joined_step = joined_step
        self.blocks = blocks or []  # paged: block ids, logical order
        self.reserved = reserved    # paged: token budget behind blocks


class BlockAllocator:
    """Fixed-size KV block pool: ``num_blocks`` blocks of
    ``block_size`` tokens each, free-list allocation.

    ``alloc`` is all-or-nothing (a request either gets its whole block
    list or stays queued — no partial reservations to unwind), and
    ``free`` rejects double-frees and foreign ids.

    Since r19 every allocated block carries a **refcount**: ``alloc``
    hands out blocks at refcount 1, ``share`` grants an additional
    holder (the radix prefix cache, or a request reusing a cached
    prefix), and ``release`` drops one reference — the block returns to
    the free list only at refcount 0.  ``free`` is ``release`` under
    its historical name, so single-holder callers behave exactly as
    before (including the double-free guard).  Shared blocks are
    strictly read-shared: only *full prompt-prefix* blocks are ever
    shared, and no decode or verify write targets a row inside them.
    """

    def __init__(self, num_blocks, block_size):
        if num_blocks < 1 or block_size < 1:
            raise MXNetError("num_blocks and block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free = list(range(self.num_blocks - 1, -1, -1))  # pop()->0
        self._in_use = set()
        self._refs = {}             # block id -> holder count (>= 1)
        self._peak_in_use = 0
        self._peak_shared = 0

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        return len(self._in_use)

    @property
    def peak_blocks_in_use(self):
        return self._peak_in_use

    @property
    def shared_blocks(self):
        """Blocks currently held by more than one owner."""
        return sum(1 for c in self._refs.values() if c > 1)

    @property
    def peak_shared_blocks(self):
        return self._peak_shared

    def refcount(self, block):
        """Holder count for ``block`` (0 when free)."""
        return self._refs.get(block, 0)

    def alloc(self, n):
        """Claim ``n`` blocks (ascending ids) at refcount 1.  Returns
        the id list, or None when the pool cannot cover the request
        (all-or-nothing)."""
        if n < 0:
            raise MXNetError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._in_use.update(blocks)
        for b in blocks:
            self._refs[b] = 1
        self._peak_in_use = max(self._peak_in_use, len(self._in_use))
        return blocks

    def share(self, blocks):
        """Grant one additional reference to each of ``blocks``.  Every
        block must already be allocated — sharing a free block would
        resurrect contents the pool no longer guarantees."""
        for b in blocks:
            if b not in self._in_use:
                raise MXNetError(f"cannot share free block {b}")
        for b in blocks:
            self._refs[b] += 1
        self._peak_shared = max(self._peak_shared, self.shared_blocks)

    def release(self, blocks):
        """Drop one reference from each of ``blocks``; a block returns
        to the free list only when its last holder lets go.  Unknown /
        already-free ids raise (the no-double-assignment invariant's
        enforcement edge, unchanged from the pre-refcount ``free``)."""
        for b in blocks:
            if b not in self._in_use:
                raise MXNetError(f"block {b} is not allocated")
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._in_use.discard(b)
                self._free.append(b)

    def free(self, blocks):
        """Historical name for :meth:`release` (identical semantics for
        refcount-1 blocks, which is every block before r19)."""
        self.release(blocks)

    def check(self):
        free = set(self._free)
        if len(free) != len(self._free):
            raise MXNetError("duplicate ids on the free list")
        if free & self._in_use:
            raise MXNetError(
                f"blocks both free and in use: {free & self._in_use}")
        if free | self._in_use != set(range(self.num_blocks)):
            raise MXNetError("block pool lost track of blocks")
        if set(self._refs) != self._in_use:
            raise MXNetError("refcount table does not match the in-use "
                             "set")
        bad = [b for b, c in self._refs.items() if c < 1]
        if bad:
            raise MXNetError(f"allocated blocks with refcount < 1: {bad}")
        return True


class KVCacheManager:
    """Fixed-capacity slot ledger (``num_slots`` concurrent sequences),
    each slot owning a full ``max_len`` cache row."""

    def __init__(self, num_slots, max_len):
        if num_slots < 1:
            raise MXNetError("num_slots must be >= 1")
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self._free = list(range(self.num_slots - 1, -1, -1))  # pop() -> 0 first
        self._active = {}           # slot -> SlotState
        self._admits = 0
        self._evictions = 0
        self._peak_occupancy = 0
        self._peak_tokens = 0

    # -- queries --------------------------------------------------------------
    def free_slots(self):
        return len(self._free)

    def active_slots(self):
        """Occupied slot ids, ascending."""
        return sorted(self._active)

    def state(self, slot):
        return self._active[slot]

    def tokens_in_flight(self):
        """K/V rows live right now = sum of active write positions."""
        return sum(st.pos for st in self._active.values())

    def stats(self):
        """Occupancy counters plus the r11 capacity metrics: the slot
        ledger reserves ``max_len`` rows for every OCCUPIED slot, so its
        ``fragmentation`` is the fraction of those reservations holding
        no live token — the number the paged pool exists to shrink."""
        reserved = len(self._active) * self.max_len
        live = self.tokens_in_flight()
        cap = self.num_slots * self.max_len
        return {"admits": self._admits, "evictions": self._evictions,
                "occupancy": len(self._active),
                "peak_occupancy": self._peak_occupancy,
                "num_slots": self.num_slots,
                "capacity_tokens": cap,
                "tokens_in_flight": int(live),
                "peak_tokens": int(self._peak_tokens),
                "utilization": round(live / cap, 4) if cap else 0.0,
                "fragmentation": round(1.0 - live / reserved, 4)
                if reserved else 0.0}

    # -- transitions ----------------------------------------------------------
    def admit(self, request_id, prompt_len, max_new_tokens, step=0):
        """Claim a slot for a prefilled request: position starts at
        ``prompt_len`` (the first decode write lands there).  Returns
        the slot id, or None when the cache is at capacity."""
        if prompt_len + max_new_tokens > self.max_len:
            raise MXNetError(
                f"sequence budget {prompt_len}+{max_new_tokens} exceeds "
                f"cache max_len {self.max_len}")
        if not self._free:
            return None
        slot = self._free.pop()
        self._active[slot] = SlotState(request_id, prompt_len,
                                       max_new_tokens, step)
        self._admits += 1
        self._peak_occupancy = max(self._peak_occupancy, len(self._active))
        self._peak_tokens = max(self._peak_tokens, self.tokens_in_flight())
        return slot

    def advance(self, slot):
        """One decode step wrote ``slot``'s K/V at its current position:
        bump the write cursor.  (The prefill-produced first token never
        advances — its K/V lands with the next step's write.)"""
        st = self._active[slot]
        st.pos += 1
        if st.pos > self.max_len:
            raise MXNetError(f"slot {slot} overran max_len {self.max_len}")
        self._peak_tokens = max(self._peak_tokens, self.tokens_in_flight())

    def consume(self, slot):
        """One output token was emitted for ``slot``'s request.  Returns
        True when the token budget is exhausted (caller evicts)."""
        st = self._active[slot]
        st.remaining -= 1
        return st.remaining <= 0

    def evict(self, slot):
        """Release ``slot`` back to the free list."""
        if slot not in self._active:
            raise MXNetError(f"slot {slot} is not active")
        del self._active[slot]
        self._free.append(slot)
        self._evictions += 1

    def check(self):
        """Assert the ledger invariants (used by tests and debug)."""
        free = set(self._free)
        active = set(self._active)
        if free & active:
            raise MXNetError(f"slots both free and active: {free & active}")
        if free | active != set(range(self.num_slots)):
            raise MXNetError("slot ledger lost track of slots")
        for slot, st in self._active.items():
            if not 0 <= st.pos <= self.max_len:
                raise MXNetError(f"slot {slot} position {st.pos} out of "
                                 f"range [0, {self.max_len}]")
        return True


class PagedKVCacheManager:
    """Block-pool ledger: slots are still the decode batch rows (the
    step program's shape), but K/V capacity comes from a shared
    :class:`BlockAllocator` — a request is admitted only when BOTH a
    slot and its whole block list (``ceil((prompt + budget) /
    block_size)`` blocks) are available.  All transitions are
    lock-serialized: the prefill lane admits while the decode lane
    advances and evicts."""

    def __init__(self, num_slots, max_len, num_blocks, block_size):
        if num_slots < 1:
            raise MXNetError("num_slots must be >= 1")
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.block_size = int(block_size)
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.num_blocks = self.allocator.num_blocks
        #: static per-slot block-table width: the step program gathers
        #: this many blocks per slot whatever the request actually owns
        self.max_blocks = -(-self.max_len // self.block_size)
        self._free = list(range(self.num_slots - 1, -1, -1))
        self._active = {}
        self._admits = 0
        self._evictions = 0
        self._peak_occupancy = 0
        self._peak_tokens = 0
        self._lock = threading.RLock()
        #: optional :class:`~mxnet_tpu.serving.radix.RadixPrefixCache`
        #: holding its own references on cached prefix blocks; consulted
        #: by ``check()`` so the refcount invariant covers cache-held
        #: blocks too.
        self.prefix_cache = None

    # -- queries --------------------------------------------------------------
    def blocks_for(self, prompt_len, max_new_tokens):
        """Blocks a request needs for its whole lifetime (prompt rows +
        every decode write), allocated up front at admit so a running
        sequence can never stall mid-decode on pool exhaustion."""
        return -(-(prompt_len + max_new_tokens) // self.block_size)

    def can_admit(self, prompt_len, max_new_tokens):
        with self._lock:
            return bool(self._free) and \
                self.blocks_for(prompt_len, max_new_tokens) \
                <= self.allocator.free_blocks

    def free_slots(self):
        with self._lock:
            return len(self._free)

    def active_slots(self):
        with self._lock:
            return sorted(self._active)

    def state(self, slot):
        return self._active[slot]

    def tokens_in_flight(self):
        with self._lock:
            return sum(st.pos for st in self._active.values())

    def _holders(self):
        """block id -> number of active block lists containing it
        (callers hold the lock)."""
        holders = {}
        for st in self._active.values():
            for b in st.blocks:
                holders[b] = holders.get(b, 0) + 1
        return holders

    def reserved_tokens(self):
        """Token capacity reserved by active requests, counting each
        shared prefix block's capacity ONCE — the pool only spends one
        block however many requests read it."""
        with self._lock:
            total = sum(st.reserved for st in self._active.values())
            over = sum((c - 1) * self.block_size
                       for c in self._holders().values() if c > 1)
            return total - over

    def stats(self):
        """Slot counters plus pool metrics.  ``fragmentation`` here is
        *internal*: the fraction of allocated block capacity not yet
        holding a live token (tail of each request's last block + the
        decode budget allocated ahead of the write cursor)."""
        with self._lock:
            live = sum(st.pos for st in self._active.values())
            # shared prefix blocks store their rows ONCE however many
            # slots read them: subtract the duplicate holders' share so
            # utilization / fragmentation describe physical rows.
            over = sum((c - 1) * self.block_size
                       for c in self._holders().values() if c > 1)
            live_unique = live - over
            used = self.allocator.blocks_in_use
            alloc_cap = used * self.block_size
            cap = self.num_blocks * self.block_size
            return {
                "admits": self._admits, "evictions": self._evictions,
                "occupancy": len(self._active),
                "peak_occupancy": self._peak_occupancy,
                "num_slots": self.num_slots,
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "blocks_in_use": used,
                "peak_blocks_in_use": self.allocator.peak_blocks_in_use,
                "shared_blocks": self.allocator.shared_blocks,
                "peak_shared_blocks": self.allocator.peak_shared_blocks,
                "capacity_tokens": cap,
                "tokens_in_flight": int(live_unique),
                "reserved_tokens": int(self.reserved_tokens()),
                "peak_tokens": int(self._peak_tokens),
                "utilization": round(live_unique / cap, 4) if cap
                else 0.0,
                "fragmentation": round(1.0 - live_unique / alloc_cap, 4)
                if alloc_cap else 0.0,
            }

    # -- transitions ----------------------------------------------------------
    def admit(self, request_id, prompt_len, max_new_tokens, step=0,
              shared_blocks=None):
        """Claim a slot AND the request's full block list.  Returns
        ``(slot, blocks)`` or None when either is unavailable (the
        request stays queued).

        ``shared_blocks`` (r19): already-allocated prefix blocks the
        request will read instead of prefilling — the radix cache's
        lookup result, in logical order, covering whole leading blocks
        of the prompt.  They are ``share()``d (the request's own
        reference) and only the remainder of the block list is freshly
        allocated; on admit failure no references are taken."""
        if prompt_len + max_new_tokens > self.max_len:
            raise MXNetError(
                f"sequence budget {prompt_len}+{max_new_tokens} exceeds "
                f"cache max_len {self.max_len}")
        shared = list(shared_blocks) if shared_blocks else []
        need = self.blocks_for(prompt_len, max_new_tokens) - len(shared)
        if need < 0:
            raise MXNetError(
                f"{len(shared)} shared prefix blocks exceed the "
                f"request's {self.blocks_for(prompt_len, max_new_tokens)}"
                "-block budget")
        with self._lock:
            if not self._free:
                return None
            fresh = self.allocator.alloc(need)
            if fresh is None:
                return None
            if shared:
                self.allocator.share(shared)
            blocks = shared + fresh
            slot = self._free.pop()
            self._active[slot] = SlotState(
                request_id, prompt_len, max_new_tokens, step,
                blocks=blocks, reserved=prompt_len + max_new_tokens)
            self._admits += 1
            self._peak_occupancy = max(self._peak_occupancy,
                                       len(self._active))
            self._peak_tokens = max(
                self._peak_tokens,
                sum(st.pos for st in self._active.values()))
            return slot, blocks

    def advance(self, slot):
        with self._lock:
            st = self._active[slot]
            st.pos += 1
            if st.pos > st.reserved:
                raise MXNetError(
                    f"slot {slot} overran its reserved {st.reserved} "
                    "tokens")
            self._peak_tokens = max(
                self._peak_tokens,
                sum(s.pos for s in self._active.values()))

    def advance_n(self, slot, n):
        """``n`` decode/verify writes landed for ``slot`` in one
        dispatch (the k-token verify forward): bump the cursor by ``n``.
        The caller rolls back any rejected suffix with
        :meth:`truncate`."""
        if n < 0:
            raise MXNetError(f"cannot advance by {n}")
        with self._lock:
            st = self._active[slot]
            st.pos += int(n)
            if st.pos > st.reserved:
                raise MXNetError(
                    f"slot {slot} overran its reserved {st.reserved} "
                    "tokens")
            self._peak_tokens = max(
                self._peak_tokens,
                sum(s.pos for s in self._active.values()))

    def truncate(self, slot, pos):
        """Roll ``slot``'s write cursor back to ``pos`` (speculative
        rejection, or an early stop releasing unused budget).  The
        reservation shrinks to what the sequence can still need
        (``pos + remaining``) and whole blocks past the new reservation
        return to the pool; returns the released block ids.

        No device-side cleanup happens: rejected rows sit beyond the
        causal mask (``t <= pos``) until the next verify/decode write
        overwrites them — the same stale-row invariant that lets a
        fresh block skip zeroing."""
        with self._lock:
            st = self._active[slot]
            if not 0 <= pos <= st.pos:
                raise MXNetError(
                    f"truncate target {pos} outside [0, {st.pos}] for "
                    f"slot {slot}")
            st.pos = int(pos)
            st.reserved = min(st.reserved,
                              st.pos + max(int(st.remaining), 0))
            need = max(-(-st.reserved // self.block_size), 0)
            released = st.blocks[need:]
            if released:
                st.blocks = st.blocks[:need]
                self.allocator.release(released)
            return released

    def consume(self, slot):
        with self._lock:
            st = self._active[slot]
            st.remaining -= 1
            return st.remaining <= 0

    def evict(self, slot):
        """Release the slot and drop the request's reference on every
        block it held; blocks shared with the radix cache or another
        request stay allocated for the remaining holders."""
        with self._lock:
            if slot not in self._active:
                raise MXNetError(f"slot {slot} is not active")
            st = self._active.pop(slot)
            self.allocator.release(st.blocks)
            self._free.append(slot)
            self._evictions += 1
            return st.blocks

    def check(self):
        """Slot invariants + block invariants.  Since r19 block lists
        may overlap on shared prefix blocks, so the partition check
        becomes a refcount check: every allocated block's holder count
        must equal the number of active block lists containing it plus
        one if the radix prefix cache holds it, and the union of all
        holders must cover the allocator's in-use set exactly."""
        with self._lock:
            free = set(self._free)
            active = set(self._active)
            if free & active:
                raise MXNetError(
                    f"slots both free and active: {free & active}")
            if free | active != set(range(self.num_slots)):
                raise MXNetError("slot ledger lost track of slots")
            for slot, st in self._active.items():
                if not 0 <= st.pos <= st.reserved <= self.max_len:
                    raise MXNetError(
                        f"slot {slot} pos {st.pos} / reserved "
                        f"{st.reserved} out of range")
                if len(st.blocks) * self.block_size < st.reserved:
                    raise MXNetError(
                        f"slot {slot} blocks cover "
                        f"{len(st.blocks) * self.block_size} < reserved "
                        f"{st.reserved} tokens")
                if len(st.blocks) != len(set(st.blocks)):
                    raise MXNetError(
                        f"slot {slot} lists a block twice")
            holders = self._holders()
            cached = (self.prefix_cache.block_refs()
                      if self.prefix_cache is not None else {})
            union = set(holders) | set(cached)
            if union != self.allocator._in_use:
                raise MXNetError(
                    "active block lists + cached prefixes do not match "
                    "the allocator's in-use set")
            for b in union:
                want = holders.get(b, 0) + cached.get(b, 0)
                have = self.allocator.refcount(b)
                if have != want:
                    raise MXNetError(
                        f"block {b} refcount {have} != {want} holders "
                        f"({holders.get(b, 0)} slots + "
                        f"{cached.get(b, 0)} cached)")
            self.allocator.check()
            return True
