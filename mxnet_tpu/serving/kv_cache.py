"""Sliced KV-cache slot accounting for continuous-batching decode.

The decode engine holds ONE static-shape cache per layer —
``(num_slots, Hkv, max_len, head_dim)`` — compiled into a single step
program (``LlamaDecoder._step_slots_impl``).  A "slice" is one slot row
of that cache.  This manager is the host-side ledger deciding which
slot each request owns and when the slot returns to the free list:

* ``admit`` — claim a free slot for a request between decode steps
  (the continuous-batching join point).  Returns None when every slot
  is busy; the scheduler leaves the request queued.
* ``advance`` — bump the slot's position after a decode step; reports
  completion when the token budget is spent.
* ``evict`` — release the slot (sequence finished or request failed);
  the slot is immediately reusable by the next admission.

Invariants (tier-1 tested): free ∪ active = all slots, free ∩ active =
∅, a slot is never admitted twice without an evict in between, and
positions never exceed ``max_len``.  Device-side slot contents are the
engine's problem — admission's prefill scatter overwrites the whole
slot row, so stale K/V from the previous tenant is unreachable.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["KVCacheManager", "SlotState"]


class SlotState:
    """One occupied slot's bookkeeping."""

    __slots__ = ("request_id", "pos", "remaining", "joined_step")

    def __init__(self, request_id, pos, remaining, joined_step):
        self.request_id = request_id
        self.pos = pos              # next cache row the step writes
        self.remaining = remaining  # tokens still owed to the request
        self.joined_step = joined_step


class KVCacheManager:
    """Fixed-capacity slot ledger (``num_slots`` concurrent sequences)."""

    def __init__(self, num_slots, max_len):
        if num_slots < 1:
            raise MXNetError("num_slots must be >= 1")
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self._free = list(range(self.num_slots - 1, -1, -1))  # pop() -> 0 first
        self._active = {}           # slot -> SlotState
        self._admits = 0
        self._evictions = 0
        self._peak_occupancy = 0

    # -- queries --------------------------------------------------------------
    def free_slots(self):
        return len(self._free)

    def active_slots(self):
        """Occupied slot ids, ascending."""
        return sorted(self._active)

    def state(self, slot):
        return self._active[slot]

    def stats(self):
        return {"admits": self._admits, "evictions": self._evictions,
                "occupancy": len(self._active),
                "peak_occupancy": self._peak_occupancy,
                "num_slots": self.num_slots}

    # -- transitions ----------------------------------------------------------
    def admit(self, request_id, prompt_len, max_new_tokens, step=0):
        """Claim a slot for a prefilled request: position starts at
        ``prompt_len`` (the first decode write lands there).  Returns
        the slot id, or None when the cache is at capacity."""
        if prompt_len + max_new_tokens > self.max_len:
            raise MXNetError(
                f"sequence budget {prompt_len}+{max_new_tokens} exceeds "
                f"cache max_len {self.max_len}")
        if not self._free:
            return None
        slot = self._free.pop()
        self._active[slot] = SlotState(request_id, prompt_len,
                                       max_new_tokens, step)
        self._admits += 1
        self._peak_occupancy = max(self._peak_occupancy, len(self._active))
        return slot

    def advance(self, slot):
        """One decode step wrote ``slot``'s K/V at its current position:
        bump the write cursor.  (The prefill-produced first token never
        advances — its K/V lands with the next step's write.)"""
        st = self._active[slot]
        st.pos += 1
        if st.pos > self.max_len:
            raise MXNetError(f"slot {slot} overran max_len {self.max_len}")

    def consume(self, slot):
        """One output token was emitted for ``slot``'s request.  Returns
        True when the token budget is exhausted (caller evicts)."""
        st = self._active[slot]
        st.remaining -= 1
        return st.remaining <= 0

    def evict(self, slot):
        """Release ``slot`` back to the free list."""
        if slot not in self._active:
            raise MXNetError(f"slot {slot} is not active")
        del self._active[slot]
        self._free.append(slot)
        self._evictions += 1

    def check(self):
        """Assert the ledger invariants (used by tests and debug)."""
        free = set(self._free)
        active = set(self._active)
        if free & active:
            raise MXNetError(f"slots both free and active: {free & active}")
        if free | active != set(range(self.num_slots)):
            raise MXNetError("slot ledger lost track of slots")
        for slot, st in self._active.items():
            if not 0 <= st.pos <= self.max_len:
                raise MXNetError(f"slot {slot} position {st.pos} out of "
                                 f"range [0, {self.max_len}]")
        return True
