"""Continuous-batching inference serving on the predictor path.

Reference: the C predict API (SURVEY §3.5) is the reference's serving
surface; this package is the server ON TOP of it — the north star's
"heavy traffic from millions of users" entry point.  Architecture
(docs/serving.md):

    clients → RequestQueue (bounded; full → ServerOverloadedError)
            → scheduler thread: group by length bucket, pad to the
              power-of-two (batch, length) grid     [bucketing.py]
            → Predictor / gluon block / llama decode engine
            → demux to per-request Futures + telemetry records

Stateless models get dynamic batching (:class:`InferenceServer`);
llama decode gets TRUE continuous batching (:class:`GenerativeServer`):
requests are admitted into free decode slots and evicted on completion
BETWEEN decode steps, so a late request joins an in-flight batch
without restarting anyone.  Since r11 the generative path is
mesh-native and disaggregated: ``GenerativeServer(net, mesh=...)``
places weights tensor-parallel (a ``dp`` axis → independent replicas
behind one queue, least-loaded routed), K/V lives in a paged block
pool (``kv_cache.PagedKVCacheManager`` — capacity bounded by tokens in
flight, not ``max_len × slots``), and prefill/decode run as separate
lanes with explicit KV handoff (``lanes.py``).  The r8 slot ledger
(``KVCacheManager``) stays importable behind
``ServerConfig(kv_mode="slots")`` for A/B.

Observability (r12, docs/observability.md): every request can carry a
span context (``telemetry.tracing``) yielding one connected trace per
request across the queue → prefill → handoff → decode thread hops;
``ServerConfig(http_port=0)`` starts a live stdlib-HTTP endpoint
(``metrics.MetricsServer``) exposing ``/metrics`` (Prometheus text),
``/healthz`` (lane liveness + KV occupancy) and ``/requests``; and
``ServerConfig(slo={...})`` turns on per-tenant TTFT/TPOT goodput
accounting (``metrics.SLOTracker``).

Speed multipliers (r19, paged only): ``ServerConfig(draft_net=...)``
turns on greedy speculative decoding — a small draft llama proposes
``spec_k`` tokens per slot, the target scores the whole window in ONE
batched multi-position forward, and rejected suffixes roll back via
``PagedKVCacheManager.truncate`` (token-exact vs. plain decode by
construction).  ``ServerConfig(radix_cache=True)`` adds the radix
prefix cache (``radix.RadixPrefixCache``): block-aligned prompt
prefixes map to refcounted paged blocks, so requests sharing a system
prompt prefill only their novel suffix.

Quick start::

    from mxnet_tpu import serving

    srv = serving.InferenceServer(predictor,
                                  serving.ServerConfig(max_batch=8))
    with srv:
        out = srv.infer(x)          # sync
        fut = srv.submit(x2)        # async -> concurrent.futures.Future
        out2 = fut.result()
"""
from .protocol import (Request, ServerClosedError,     # noqa: F401
                       ServerOverloadedError)
from .bucketing import BucketPolicy, pad_batch, pow2_bucket  # noqa: F401
from .kv_cache import (BlockAllocator, KVCacheManager,  # noqa: F401
                       PagedKVCacheManager)
from .radix import RadixPrefixCache                    # noqa: F401
from .scheduler import BatchScheduler, RequestQueue    # noqa: F401
from .lanes import (DecodeLane, PrefillLane, Replica,  # noqa: F401
                    ReplicaDispatcher)
from .server import (GenerativeServer, InferenceServer,  # noqa: F401
                     ServerConfig)
from .metrics import (MetricsServer, SLOTracker,       # noqa: F401
                      prometheus_text)

__all__ = ["Request", "ServerOverloadedError", "ServerClosedError",
           "BucketPolicy", "pow2_bucket", "pad_batch", "KVCacheManager",
           "PagedKVCacheManager", "BlockAllocator", "RadixPrefixCache",
           "RequestQueue", "BatchScheduler", "ServerConfig",
           "InferenceServer", "GenerativeServer",
           "PrefillLane", "DecodeLane", "Replica", "ReplicaDispatcher",
           "MetricsServer", "SLOTracker", "prometheus_text"]
