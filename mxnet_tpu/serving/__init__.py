"""Continuous-batching inference serving on the predictor path.

Reference: the C predict API (SURVEY §3.5) is the reference's serving
surface; this package is the server ON TOP of it — the north star's
"heavy traffic from millions of users" entry point.  Architecture
(docs/serving.md):

    clients → RequestQueue (bounded; full → ServerOverloadedError)
            → scheduler thread: group by length bucket, pad to the
              power-of-two (batch, length) grid     [bucketing.py]
            → Predictor / gluon block / llama decode engine
            → demux to per-request Futures + telemetry records

Stateless models get dynamic batching (:class:`InferenceServer`);
llama decode gets TRUE continuous batching (:class:`GenerativeServer`):
a sliced KV cache (``kv_cache.KVCacheManager`` + one per-slot-position
compiled step) where requests are admitted into free slots and evicted
on completion BETWEEN decode steps, so a late request joins an
in-flight batch without restarting anyone.

Quick start::

    from mxnet_tpu import serving

    srv = serving.InferenceServer(predictor,
                                  serving.ServerConfig(max_batch=8))
    with srv:
        out = srv.infer(x)          # sync
        fut = srv.submit(x2)        # async -> concurrent.futures.Future
        out2 = fut.result()
"""
from .protocol import (Request, ServerClosedError,     # noqa: F401
                       ServerOverloadedError)
from .bucketing import BucketPolicy, pad_batch, pow2_bucket  # noqa: F401
from .kv_cache import KVCacheManager                   # noqa: F401
from .scheduler import BatchScheduler, RequestQueue    # noqa: F401
from .server import (GenerativeServer, InferenceServer,  # noqa: F401
                     ServerConfig)

__all__ = ["Request", "ServerOverloadedError", "ServerClosedError",
           "BucketPolicy", "pow2_bucket", "pad_batch", "KVCacheManager",
           "RequestQueue", "BatchScheduler", "ServerConfig",
           "InferenceServer", "GenerativeServer"]
