"""Disaggregated prefill/decode execution lanes over paged KV.

`SERVING_LATENCY_r08.json` showed the r8 single-loop server queue-bound
(queue-wait was 52.7 of 53.7 ms closed-loop p99): one thread interleaves
compute-bound prompt prefills with latency-bound decode ticks, so every
long prompt stalls every in-flight decode.  This module splits the two
phases into lanes with their own scheduler threads and batch policies,
connected by an explicit KV handoff:

* :class:`PrefillLane` — batch-tolerant.  Pulls the FIFO-head prompt
  bucket from the replica queue, gated by the paged-KV admission budget
  (free decode slots, free KV blocks, a cumulative prompt-token ceiling
  — prefill batches greedily by token count, not request count), admits
  each request to the :class:`~.kv_cache.PagedKVCacheManager` (which
  reserves the request's whole block budget up front — no mid-decode
  allocation stall), runs the prompt forward OUTSIDE the engine's
  device lock, then commits the raw K/V rows into the admitted blocks
  (one brief locked scatter) and hands the slot to the decode lane.
* :class:`DecodeLane` — latency-structured.  Every tick it adopts
  pending handoffs, then advances *its own* slot set one token.  It
  never sees a prompt forward: while a long prompt prefills, decode
  ticks keep dispatching (the device lock covers only the KV-mutating
  dispatches, not the prefill compute).
* :class:`Replica` — one engine + manager + lane pair over one (tp)
  submesh.  A dp mesh axis becomes N independent replicas behind one
  front queue, routed by :class:`ReplicaDispatcher` to the
  least-loaded replica (by reserved + queued tokens).

Host-sync discipline: the decode drain and the handoff boundary block
on device results in :func:`_lane_materialize` ONLY — the lane twin of
``scheduler._materialize``, exempted by name in tools/lint
(``MATERIALIZE_DEFS``); syncs anywhere else in the lanes still flag.

Telemetry: requests carry ``replica``/``handoff_ms``/``kv_blocks`` in
their JSONL records, lanes emit ``serving.prefill`` spans and
``serving.handoff_ms`` histograms, and the decode tick publishes the
``serving.kv_blocks_in_use`` gauge (see docs/observability.md).

Tracing (r12): when ``telemetry.tracing`` is on, each request carries
its span context across the lane threads (``req.trace``): the prefill
lane records the ``queue`` and ``prefill`` spans at admission, adoption
records ``handoff``, every decode tick records one ``decode.step`` span
per traced slot, and :meth:`Replica.finish` seals the trace (``evict``
event + the root span) — all retroactive from stamps the lanes already
take, so the decode tick pays one dict append per traced slot.  The
failure paths emit ``status="error"`` request records tagged with
replica + lane and trip the flight recorder (``tracing.incident``).
"""
from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from .. import sanitizer as _san
from .. import telemetry
from ..telemetry import tracing
from .bucketing import pad_batch
from .kv_cache import PagedKVCacheManager
from .protocol import ServerClosedError
from .scheduler import RequestQueue

__all__ = ["PrefillLane", "DecodeLane", "Replica", "ReplicaDispatcher"]


def _lane_materialize(arrays):
    """The lanes' designated device→host sync point (first tokens at
    the prefill→decode handoff, token vectors at each decode tick) —
    the only def in this module sanctioned for eager syncs by
    tools/lint's ``MATERIALIZE_DEFS``, mirroring
    ``scheduler._materialize``."""
    out = []
    for a in arrays:
        if hasattr(a, "asnumpy"):
            out.append(a.asnumpy())
        else:
            out.append(np.asarray(a))
    return out


class _Handoff:
    """One admitted request crossing the prefill→decode boundary: its
    KV rows are already scattered into its blocks; the decode lane just
    adopts the slot."""

    __slots__ = ("req", "slot", "first")

    def __init__(self, req, slot, first):
        self.req = req
        self.slot = slot
        self.first = first


class PrefillLane:
    """Admission + prompt forward + KV commit, one thread per replica."""

    def __init__(self, replica, poll_s=0.02):
        self.r = replica
        self.poll_s = float(poll_s)
        self._stop = threading.Event()
        self._drain = True
        self._thread = None
        self.error = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop,
                name=f"mxt-prefill-r{self.r.index}", daemon=True)
            self._thread.start()

    def request_stop(self, drain=True):
        self._drain = drain
        self._stop.set()

    def join(self):
        """Join the lane thread; a captured lane-machinery error is
        re-raised here — the lane's materialization point."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self.error is not None:
            raise self.error

    def alive(self):
        """Lane-thread liveness (the /healthz signal)."""
        return self._thread is not None and self._thread.is_alive()

    def _loop(self):
        # per-request failures are handled inside _admit_batch; this
        # catches lane-machinery bugs so the thread never dies silently
        try:
            self._run()
        except Exception as exc:
            self.error = exc
            tracing.incident("lane_thread_error",
                             context={"replica": self.r.index,
                                      "lane": "prefill",
                                      "error": repr(exc)})

    def _run(self):
        q = self.r.queue
        while True:
            if self._stop.is_set():
                if not self._drain or not len(q):
                    break
            if not self._admit_batch() and not self._stop.is_set():
                if len(q):
                    # queue non-empty but gated on capacity: wait for an
                    # eviction to free slots/blocks (wait_for_item would
                    # return immediately and busy-spin against decode)
                    self.r.capacity_evt.wait(self.poll_s)
                    self.r.capacity_evt.clear()
                else:
                    q.wait_for_item(self.poll_s)

    def _bucket(self, req):
        return self.r.policy.length_bucket(len(req.prompt_ids))

    def _admit_batch(self):
        """One prefill batch: gate → admit → forward (unlocked) →
        commit (locked) → handoff.  Returns True if anything ran."""
        r = self.r
        mgr = r.mgr
        free_slots = mgr.free_slots()
        if not free_slots or not len(r.queue):
            return False
        free_blocks = mgr.allocator.free_blocks
        budget = {"n": 0, "blocks": 0, "tokens": 0}

        def accept(req):
            # the lane's own batch policy: greedy by token count under
            # the block budget, not a fixed request count
            need = mgr.blocks_for(len(req.prompt_ids),
                                  req.max_new_tokens)
            if budget["n"] >= free_slots:
                return False
            if budget["blocks"] + need > free_blocks:
                return False
            if budget["tokens"] and (budget["tokens"]
                                     + len(req.prompt_ids)
                                     > r.max_prefill_tokens):
                return False
            budget["n"] += 1
            budget["blocks"] += need
            budget["tokens"] += len(req.prompt_ids)
            return True

        group = r.queue.take_batch(
            self._bucket, min(free_slots, r.policy.max_batch), accept)
        if not group:
            return False
        t_start = time.perf_counter()
        lb = self._bucket(group[0])
        kb = r.policy.batch_bucket(len(group))
        eng = r.engine
        try:
            prompts = pad_batch([np.asarray(q.prompt_ids, np.int32)
                                 for q in group], kb, lb)
            t0s = np.full(kb, len(group[0].prompt_ids), np.int32)
            slots = np.full(kb, eng.num_slots, np.int32)
            block_lists = [None] * kb
            for i, req in enumerate(group):
                t0s[i] = len(req.prompt_ids)
                slot, blocks = mgr.admit(req.id, int(t0s[i]),
                                         req.max_new_tokens,
                                         step=eng.steps)
                slots[i] = slot
                block_lists[i] = blocks
                req.slot = int(slot)
                req.kv_blocks = len(blocks)
                req.replica = r.index
                req.joined_step = eng.steps
                req.t_start = t_start
                req.bucket = (kb, lb)
                req.batch_size = len(group)
            with telemetry.span("serving.prefill",
                                {"lane": "prefill", "replica": r.index,
                                 "batch": kb, "length": lb}):
                toks, rows = eng.prefill_rows(prompts, t0s)
                first = _lane_materialize([toks])[0]
                eng.commit_rows(rows, slots, block_lists, t0s, first)
        except Exception as exc:
            for req in group:
                if req.slot is not None and req.slot in mgr._active:
                    mgr.evict(req.slot)
                    eng.clear_slot(req.slot)
                req.replica = r.index
                req.future.set_exception(exc)
                r.fail(req, exc, lane="prefill")
            r.capacity_evt.set()
            tracing.incident("replica_exception",
                             context={"replica": r.index,
                                      "lane": "prefill",
                                      "error": repr(exc)})
            return True
        t_first = time.perf_counter()
        mates = [req.id for req in group]
        for i, req in enumerate(group):
            req.t_first = t_first
            if req.trace is not None:
                # retroactive spans from the stamps above: queue covers
                # dispatch + bucket dwell, prefill the forward + commit
                req.trace.add("queue", req.t_submit, t_start,
                              replica=r.index)
                req.trace.add("prefill", t_start, t_first,
                              replica=r.index, slot=req.slot,
                              kv_blocks=req.kv_blocks,
                              bucket=list(req.bucket),
                              mates=[m for m in mates if m != req.id])
            if mgr.consume(req.slot):
                # max_new_tokens == 1: done at prefill, never decodes
                r.finish(req, [int(first[i])])
            else:
                r.decode.hand_off(_Handoff(req, req.slot,
                                           int(first[i])))
        telemetry.count("serving.admitted", len(group))
        return True


class DecodeLane:
    """Slot-set advancement, one thread per replica: adopt handoffs,
    tick every in-flight slot, evict finished requests (returning their
    KV blocks to the pool)."""

    def __init__(self, replica, poll_s=0.005):
        self.r = replica
        self.poll_s = float(poll_s)
        self._handoffs = deque()
        self._hand_lock = _san.wrap_lock(
            threading.Lock(), "lanes.DecodeLane._hand_lock")
        self._seqs = {}       # slot -> (request, [generated tokens])
        self._wake = threading.Event()   # set on hand_off: adopt now
        self._stop = threading.Event()
        self._thread = None
        self.error = None

    def hand_off(self, h):
        with self._hand_lock:
            self._handoffs.append(h)
        self._wake.set()

    def pending(self):
        with self._hand_lock:
            return len(self._handoffs) + len(self._seqs)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop,
                name=f"mxt-decode-r{self.r.index}", daemon=True)
            self._thread.start()

    def request_stop(self):
        self._stop.set()

    def join(self):
        """Join the lane thread; a captured lane-machinery error is
        re-raised here — the lane's materialization point."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self.error is not None:
            raise self.error

    def alive(self):
        """Lane-thread liveness (the /healthz signal)."""
        return self._thread is not None and self._thread.is_alive()

    def snapshot(self):
        """In-flight view for the /requests table: handoffs not yet
        adopted + decoding slots, host-side bookkeeping only."""
        rows = []
        with self._hand_lock:
            handoffs = list(self._handoffs)
            seqs = dict(self._seqs)
        for h in handoffs:
            rows.append({"request_id": h.req.id, "state": "handoff",
                         "slot": h.slot, "replica": self.r.index})
        for slot, (req, tokens) in seqs.items():
            rows.append({"request_id": req.id, "state": "decoding",
                         "slot": slot, "replica": self.r.index,
                         "tokens_done": len(tokens),
                         "max_new_tokens": req.max_new_tokens})
        return rows

    def _loop(self):
        # per-request failures are handled inside _tick; this catches
        # lane-machinery bugs so the thread never dies silently
        try:
            self._run()
        except Exception as exc:
            self.error = exc
            tracing.incident("lane_thread_error",
                             context={"replica": self.r.index,
                                      "lane": "decode",
                                      "error": repr(exc)})

    def _run(self):
        while True:
            self._adopt()
            with self._hand_lock:
                busy = bool(self._seqs)
            if busy:
                self._tick()
            elif self._stop.is_set():
                if not self.pending():
                    break
            else:
                self._wake.wait(self.poll_s)
                self._wake.clear()

    def _adopt(self):
        """Pull every pending handoff into this lane's slot set.  The
        KV rows are already in the request's blocks (the prefill lane
        committed them before handing off), so adoption is pure
        bookkeeping — decode only ever advances slots it has adopted,
        never a slot whose commit is still in flight."""
        while True:
            with self._hand_lock:
                if not self._handoffs:
                    return
                h = self._handoffs.popleft()
            h.req.t_handoff = time.perf_counter()
            hand_ms = (h.req.t_handoff - h.req.t_first) * 1e3
            telemetry.hist("serving.handoff_ms", hand_ms)
            telemetry.hist(f"serving.handoff_ms|replica={self.r.index}",
                           hand_ms)
            if h.req.trace is not None:
                h.req.trace.add("handoff", h.req.t_first,
                                h.req.t_handoff, replica=self.r.index,
                                slot=h.slot)
            with self._hand_lock:
                self._seqs[h.slot] = (h.req, [h.first])

    def _tick(self):
        r = self.r
        with self._hand_lock:
            active = sorted(self._seqs)
        t0 = time.perf_counter()
        try:
            toks = r.engine.step(active)
        except Exception as exc:
            for slot in active:
                with self._hand_lock:
                    req, _ = self._seqs.pop(slot)
                r.mgr.evict(slot)
                r.engine.clear_slot(slot)
                req.future.set_exception(exc)
                r.fail(req, exc, lane="decode")
            r.capacity_evt.set()
            tracing.incident("replica_exception",
                             context={"replica": r.index,
                                      "lane": "decode",
                                      "error": repr(exc)})
            return
        t1 = time.perf_counter()
        r.batches += 1
        telemetry.hist("serving.batch_size", len(active))
        telemetry.gauge("serving.kv_blocks_in_use",
                        r.mgr.allocator.blocks_in_use)
        step_idx = r.engine.steps
        for slot in active:
            r.mgr.advance(slot)   # the step wrote K/V at slot's pos
            with self._hand_lock:
                req, tokens = self._seqs[slot]
            tokens.append(int(toks[slot]))
            if req.trace is not None:
                # one span per traced slot per tick: the per-request
                # decode slice (cost: one dict append — the tracing
                # A/B lane in benchmark/serving_latency.py bounds it)
                req.trace.add("decode.step", t0, t1, step=step_idx,
                              batch=len(active), replica=r.index,
                              slot=slot)
            if r.mgr.consume(slot):
                with self._hand_lock:
                    del self._seqs[slot]
                r.finish(req, tokens)


class Replica:
    """One model replica: engine + paged-KV manager + lane pair over
    one (tp) submesh, fed by a bounded internal queue."""

    def __init__(self, net, policy, index=0, mesh=None,
                 partition_rules=None, num_slots=4, int8=False,
                 block_size=16, num_blocks=None, queue_capacity=64,
                 max_prefill_tokens=None, summary_every=32, slo=None):
        from .generative import LlamaServingEngine

        self.index = int(index)
        self.policy = policy
        self.engine = LlamaServingEngine(
            net, max_len=policy.max_length, num_slots=num_slots,
            int8=int8, kv_mode="paged", block_size=block_size,
            num_blocks=num_blocks, mesh=mesh,
            partition_rules=partition_rules, replica_id=self.index)
        self.mgr = PagedKVCacheManager(
            num_slots, policy.max_length,
            num_blocks=self.engine.num_blocks,
            block_size=self.engine.block_size)
        self.queue = RequestQueue(queue_capacity)
        self.max_prefill_tokens = int(max_prefill_tokens or
                                      policy.max_batch
                                      * policy.max_length)
        self.summary_every = int(summary_every)
        self.prefill = PrefillLane(self)
        self.decode = DecodeLane(self)
        self.capacity_evt = threading.Event()  # set on evict: re-admit
        self.slo = slo   # shared SLOTracker (metrics.py) or None
        self.completed = 0
        self.failed = 0
        self.batches = 0

    # -- dispatcher-facing ----------------------------------------------------
    def load(self):
        """Routing weight: tokens reserved in the KV pool plus tokens
        waiting in the internal queue."""
        queued = self.queue.queued_tokens(
            lambda r: len(r.prompt_ids) + r.max_new_tokens)
        return self.mgr.reserved_tokens() + queued

    def offer(self, req):
        return self.queue.offer(req)

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        self.prefill.start()
        self.decode.start()

    def stop(self, drain=True):
        """Drain order matters: prefill first (with decode still live,
        so draining admissions can wait for blocks decode will free),
        then decode finishes the in-flight slot set."""
        self.queue.close()
        self.prefill.request_stop(drain)
        self.prefill.join()
        self.decode.request_stop()
        self.decode.join()
        for req in self.queue.take_group(lambda r: 0, 1 << 30):
            req.future.set_exception(
                ServerClosedError("server stopped before execution"))

    # -- completion -----------------------------------------------------------
    def finish(self, req, tokens):
        self.mgr.evict(req.slot)
        self.engine.clear_slot(req.slot)
        self.capacity_evt.set()
        req.t_done = time.perf_counter()
        req.done_step = self.engine.steps
        n = req.max_new_tokens
        req.future.set_result(np.concatenate(
            [np.asarray(req.prompt_ids, np.int32),
             np.asarray(tokens[:n], np.int32)]))
        self.completed += 1
        telemetry.count("serving.completed")
        telemetry.count(f"serving.completed|replica={self.index}")
        lane = "decode" if req.t_handoff is not None else "prefill"
        rec = req.record(lane=lane)
        tag = f"|replica={self.index}"
        if rec["queue_wait_ms"] is not None:
            telemetry.hist("serving.queue_wait_ms", rec["queue_wait_ms"])
            telemetry.hist("serving.queue_wait_ms" + tag,
                           rec["queue_wait_ms"])
        if rec["total_ms"] is not None:
            telemetry.hist("serving.total_ms", rec["total_ms"])
            telemetry.hist("serving.total_ms" + tag, rec["total_ms"])
        if rec.get("ttft_ms") is not None:
            telemetry.hist("serving.ttft_ms", rec["ttft_ms"])
            telemetry.hist("serving.ttft_ms" + tag, rec["ttft_ms"])
        if rec.get("tpot_ms") is not None:
            telemetry.hist("serving.tpot_ms", rec["tpot_ms"])
            telemetry.hist("serving.tpot_ms" + tag, rec["tpot_ms"])
        if self.slo is not None:
            rec["slo_met"] = self.slo.observe(
                tenant=req.tenant, ttft_ms=rec.get("ttft_ms"),
                tpot_ms=rec.get("tpot_ms"))
        telemetry.emit(rec)
        if req.trace is not None:
            req.trace.event("evict", replica=self.index, slot=req.slot)
            tracing.finish(req.trace, status="ok", replica=self.index,
                           lane=lane, request_id=req.id)
        if self.summary_every and self.completed % self.summary_every == 0:
            self.emit_summary()

    def fail(self, req, exc, lane):
        """Failure-path accounting: the ``status="error"`` request
        record (tagged replica + lane — the eviction/rejection paths
        used to drop both), the failed counters, and the trace seal."""
        self.failed += 1
        telemetry.count("serving.failed")
        telemetry.count(f"serving.failed|replica={self.index}")
        req.t_done = time.perf_counter()
        telemetry.emit(req.record(lane=lane, status="error",
                                  error=repr(exc)))
        if req.trace is not None:
            tracing.finish(req.trace, status="error",
                           replica=self.index, lane=lane,
                           error=repr(exc), request_id=req.id)

    def emit_summary(self):
        telemetry.emit({
            "record": "serving.latency",
            "replica": self.index,
            "completed": self.completed,
            "failed": self.failed,
            "batches": self.batches,
            "queue_wait_ms": telemetry.hist_summary("serving.queue_wait_ms"),
            "total_ms": telemetry.hist_summary("serving.total_ms"),
            "ttft_ms": telemetry.hist_summary("serving.ttft_ms"),
            "handoff_ms": telemetry.hist_summary("serving.handoff_ms"),
            "batch_size": telemetry.hist_summary("serving.batch_size"),
            "kv_cache": self.mgr.stats(),
        })


class ReplicaDispatcher:
    """Routes the front queue to the least-loaded replica.

    One thread pops the FIFO head and offers it to the replica with the
    smallest :meth:`Replica.load` that has internal queue space; if all
    replica queues are full the head is held (client backpressure
    already happened at the front queue's bounded ``put``)."""

    def __init__(self, queue, replicas, poll_s=0.005):
        self.queue = queue
        self.replicas = list(replicas)
        self.poll_s = float(poll_s)
        self._held = None
        self._stop = threading.Event()
        self._drain = True
        self._thread = None
        self.error = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="mxt-dispatch",
                                            daemon=True)
            self._thread.start()

    def stop(self, drain=True):
        self._drain = drain
        self._stop.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self.error is not None:
            raise self.error
        leftovers = ([self._held] if self._held is not None else []) \
            + self.queue.take_group(lambda r: 0, 1 << 30)
        self._held = None
        for req in leftovers:
            if drain:
                while not self._route(req):
                    time.sleep(self.poll_s)
            else:
                req.future.set_exception(
                    ServerClosedError("server stopped before execution"))

    def _route(self, req):
        for rep in sorted(self.replicas, key=lambda r: r.load()):
            if rep.offer(req):
                return True
        return False

    def _loop(self):
        # catches dispatcher bugs so the routing thread never dies
        # silently; re-raised at stop()
        try:
            self._run()
        except Exception as exc:
            self.error = exc
            tracing.incident("dispatcher_thread_error",
                             context={"error": repr(exc)})

    def _run(self):
        while not self._stop.is_set():
            if self._held is None:
                group = self.queue.take_group(lambda r: 0, 1)
                if not group:
                    self.queue.wait_for_item(self.poll_s)
                    continue
                self._held = group[0]
            if self._route(self._held):
                self._held = None
            else:
                time.sleep(self.poll_s)
