"""Disaggregated prefill/decode execution lanes over paged KV.

`SERVING_LATENCY_r08.json` showed the r8 single-loop server queue-bound
(queue-wait was 52.7 of 53.7 ms closed-loop p99): one thread interleaves
compute-bound prompt prefills with latency-bound decode ticks, so every
long prompt stalls every in-flight decode.  This module splits the two
phases into lanes with their own scheduler threads and batch policies,
connected by an explicit KV handoff:

* :class:`PrefillLane` — batch-tolerant.  Pulls the FIFO-head prompt
  bucket from the replica queue, gated by the paged-KV admission budget
  (free decode slots, free KV blocks, a cumulative prompt-token ceiling
  — prefill batches greedily by token count, not request count), admits
  each request to the :class:`~.kv_cache.PagedKVCacheManager` (which
  reserves the request's whole block budget up front — no mid-decode
  allocation stall), runs the prompt forward OUTSIDE the engine's
  device lock, then commits the raw K/V rows into the admitted blocks
  (one brief locked scatter) and hands the slot to the decode lane.
* :class:`DecodeLane` — latency-structured.  Every tick it adopts
  pending handoffs, then advances *its own* slot set one token.  It
  never sees a prompt forward: while a long prompt prefills, decode
  ticks keep dispatching (the device lock covers only the KV-mutating
  dispatches, not the prefill compute).
* :class:`Replica` — one engine + manager + lane pair over one (tp)
  submesh.  A dp mesh axis becomes N independent replicas behind one
  front queue, routed by :class:`ReplicaDispatcher` to the
  least-loaded replica (by reserved + queued tokens).

Host-sync discipline: the decode drain and the handoff boundary block
on device results in :func:`_lane_materialize` ONLY — the lane twin of
``scheduler._materialize``, exempted by name in tools/lint
(``MATERIALIZE_DEFS``); syncs anywhere else in the lanes still flag.

Telemetry: requests carry ``replica``/``handoff_ms``/``kv_blocks`` in
their JSONL records, lanes emit ``serving.prefill`` spans and
``serving.handoff_ms`` histograms, and the decode tick publishes the
``serving.kv_blocks_in_use`` gauge (see docs/observability.md).

Tracing (r12): when ``telemetry.tracing`` is on, each request carries
its span context across the lane threads (``req.trace``): the prefill
lane records the ``queue`` and ``prefill`` spans at admission, adoption
records ``handoff``, every decode tick records one ``decode.step`` span
per traced slot, and :meth:`Replica.finish` seals the trace (``evict``
event + the root span) — all retroactive from stamps the lanes already
take, so the decode tick pays one dict append per traced slot.  The
failure paths emit ``status="error"`` request records tagged with
replica + lane and trip the flight recorder (``tracing.incident``).
"""
from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from .. import sanitizer as _san
from .. import telemetry
from ..telemetry import capacity
from ..telemetry import tracing
from .bucketing import pad_batch
from .kv_cache import PagedKVCacheManager
from .protocol import ServerClosedError
from .scheduler import RequestQueue

__all__ = ["PrefillLane", "DecodeLane", "Replica", "ReplicaDispatcher"]


def _lane_materialize(arrays):
    """The lanes' designated device→host sync point (first tokens at
    the prefill→decode handoff, token vectors at each decode tick) —
    the only def in this module sanctioned for eager syncs by
    tools/lint's ``MATERIALIZE_DEFS``, mirroring
    ``scheduler._materialize``."""
    out = []
    for a in arrays:
        if hasattr(a, "asnumpy"):
            out.append(a.asnumpy())
        else:
            out.append(np.asarray(a))
    return out


class _Handoff:
    """One admitted request crossing the prefill→decode boundary: its
    KV rows are already scattered into its blocks; the decode lane just
    adopts the slot."""

    __slots__ = ("req", "slot", "first")

    def __init__(self, req, slot, first):
        self.req = req
        self.slot = slot
        self.first = first


class PrefillLane:
    """Admission + prompt forward + KV commit, one thread per replica."""

    def __init__(self, replica, poll_s=0.02):
        self.r = replica
        self.poll_s = float(poll_s)
        self._stop = threading.Event()
        self._drain = True
        self._thread = None
        self.error = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop,
                name=f"mxt-prefill-r{self.r.index}", daemon=True)
            self._thread.start()

    def request_stop(self, drain=True):
        self._drain = drain
        self._stop.set()

    def join(self):
        """Join the lane thread; a captured lane-machinery error is
        re-raised here — the lane's materialization point."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self.error is not None:
            raise self.error

    def alive(self):
        """Lane-thread liveness (the /healthz signal)."""
        return self._thread is not None and self._thread.is_alive()

    def _loop(self):
        # per-request failures are handled inside _admit_batch; this
        # catches lane-machinery bugs so the thread never dies silently
        try:
            self._run()
        except Exception as exc:
            self.error = exc
            tracing.incident("lane_thread_error",
                             context={"replica": self.r.index,
                                      "lane": "prefill",
                                      "error": repr(exc)})

    def _run(self):
        q = self.r.queue
        while True:
            if self._stop.is_set():
                if not self._drain or not len(q):
                    break
            if not self._admit_batch() and not self._stop.is_set():
                if len(q):
                    # queue non-empty but gated on capacity: wait for an
                    # eviction to free slots/blocks (wait_for_item would
                    # return immediately and busy-spin against decode)
                    self.r.capacity_evt.wait(self.poll_s)
                    self.r.capacity_evt.clear()
                else:
                    q.wait_for_item(self.poll_s)

    def _bucket(self, req):
        """Prompt-length bucket — of the NOVEL SUFFIX when the radix
        prefix cache is on (the prefill program only sees the suffix;
        ``match_len`` is non-mutating, so bucketing probes don't churn
        LRU state).  The prefill thread is the trie's only mutator, so
        the probe here and the real lookup in ``_admit_batch`` agree."""
        plen = len(req.prompt_ids)
        if self.r.radix is not None:
            plen -= self.r.radix.match_len(req.prompt_ids)
        return self.r.policy.length_bucket(plen)

    def _admit_batch(self):
        """One prefill batch: gate → admit → forward (unlocked) →
        commit (locked) → handoff.  Returns True if anything ran."""
        r = self.r
        mgr = r.mgr
        free_slots = mgr.free_slots()
        if not free_slots or not len(r.queue):
            return False
        free_blocks = mgr.allocator.free_blocks
        budget = {"n": 0, "blocks": 0, "tokens": 0}

        def accept(req):
            # the lane's own batch policy: greedy by token count under
            # the block budget, not a fixed request count (a radix hit
            # shrinks the fresh-block need by the shared prefix)
            need = mgr.blocks_for(len(req.prompt_ids),
                                  req.max_new_tokens)
            if r.radix is not None:
                need -= r.radix.match_len(req.prompt_ids) \
                    // mgr.block_size
            if budget["n"] >= free_slots:
                return False
            if budget["blocks"] + need > free_blocks:
                return False
            if budget["tokens"] and (budget["tokens"]
                                     + len(req.prompt_ids)
                                     > r.max_prefill_tokens):
                return False
            budget["n"] += 1
            budget["blocks"] += need
            budget["tokens"] += len(req.prompt_ids)
            return True

        group = r.queue.take_batch(
            self._bucket, min(free_slots, r.policy.max_batch), accept)
        if not group:
            return False
        t_start = time.perf_counter()
        lb = self._bucket(group[0])
        kb = r.policy.batch_bucket(len(group))
        eng = r.engine
        rx = r.radix
        try:
            if rx is not None:
                # real lookup (bumps LRU, counts hits); no references
                # are taken until admit() shares under the manager lock
                t_rx0 = time.perf_counter()
                matched, shared = [], []
                for req in group:
                    m, blks = rx.lookup(req.prompt_ids)
                    matched.append(m)
                    shared.append(blks)
                t_rx1 = time.perf_counter()
                hits = sum(1 for m in matched if m)
                telemetry.count("serving.radix_hits", hits)
                telemetry.count("serving.radix_misses",
                                len(group) - hits)
                if any(matched):
                    telemetry.count("serving.radix_hit_tokens",
                                    sum(matched))
            else:
                t_rx0 = t_rx1 = t_start
                matched = [0] * len(group)
                shared = [None] * len(group)
            prompts = pad_batch(
                [np.asarray(q.prompt_ids[matched[i]:], np.int32)
                 for i, q in enumerate(group)], kb, lb)
            t0s = np.full(kb, len(group[0].prompt_ids), np.int32)
            t0s_suf = np.full(
                kb, len(group[0].prompt_ids) - matched[0], np.int32)
            s0s = np.zeros(kb, np.int32)
            skip = np.zeros(kb, np.int32)
            slots = np.full(kb, eng.num_slots, np.int32)
            block_lists = [None] * kb
            for i, req in enumerate(group):
                t0s[i] = len(req.prompt_ids)
                t0s_suf[i] = t0s[i] - matched[i]
                s0s[i] = matched[i]
                skip[i] = matched[i] // mgr.block_size
                slot, blocks = mgr.admit(req.id, int(t0s[i]),
                                         req.max_new_tokens,
                                         step=eng.steps,
                                         shared_blocks=shared[i] or None)
                slots[i] = slot
                block_lists[i] = blocks
                req.slot = int(slot)
                req.kv_blocks = len(blocks)
                if rx is not None:
                    req.prefix_hit_tokens = matched[i]
                req.replica = r.index
                req.joined_step = eng.steps
                req.t_start = t_start
                req.bucket = (kb, lb)
                req.batch_size = len(group)
            with telemetry.span("serving.prefill",
                                {"lane": "prefill", "replica": r.index,
                                 "batch": kb, "length": lb}):
                if rx is not None and any(matched):
                    # radix-hit path: dense prefix copies (locked
                    # gather) feed the suffix-only forward (unlocked);
                    # the commit scatters ONLY the suffix rows into the
                    # request's private blocks past the shared prefix
                    pre_lb = r.policy.length_bucket(max(matched))
                    nbp_pre = -(-pre_lb // mgr.block_size)
                    rows_idx = np.full((kb, nbp_pre), eng.num_blocks,
                                       np.int32)
                    for i in range(len(group)):
                        rows_idx[i, :skip[i]] = \
                            block_lists[i][:skip[i]]
                    pre_kv = eng.gather_prefix(rows_idx)
                    toks, rows = eng.prefill_suffix(pre_kv, prompts,
                                                    t0s_suf, s0s)
                else:
                    toks, rows = eng.prefill_rows(prompts, t0s_suf)
                first = _lane_materialize([toks])[0]
                eng.commit_rows(rows, slots, block_lists, t0s, first,
                                skip_blocks=skip)
            if rx is not None:
                # register the full prompt blocks (device-ordered after
                # the commit scatter) so later requests share them
                for i, req in enumerate(group):
                    rx.insert(req.prompt_ids, block_lists[i])
            if r.draft is not None:
                # the draft engine prefills the FULL prompt into its
                # own slot caches, then aligns its mirror with the
                # target's first token (draft.admit picked its own)
                lbf = r.policy.length_bucket(
                    max(len(q.prompt_ids) for q in group))
                fulls = pad_batch([np.asarray(q.prompt_ids, np.int32)
                                   for q in group], kb, lbf)
                r.draft.admit(fulls, t0s, slots)
                for i in range(len(group)):
                    s = int(slots[i])
                    if s < eng.num_slots:
                        r.draft.set_mirror(s, int(first[i]),
                                           int(t0s[i]))
        except Exception as exc:
            for req in group:
                if req.slot is not None and req.slot in mgr._active:
                    mgr.evict(req.slot)
                    eng.clear_slot(req.slot)
                req.replica = r.index
                req.future.set_exception(exc)
                r.fail(req, exc, lane="prefill")
            r.capacity_evt.set()
            tracing.incident("replica_exception",
                             context={"replica": r.index,
                                      "lane": "prefill",
                                      "error": repr(exc)})
            return True
        t_first = time.perf_counter()
        # retroactive prefill duty-cycle interval from the stamps the
        # lane already took (same contract as the trace spans below)
        capacity.lane_busy(r.index, "prefill", t_start, t_first)
        mates = [req.id for req in group]
        for i, req in enumerate(group):
            req.t_first = t_first
            if rx is not None and matched[i] and t0s_suf[i] > 0:
                # prefill cost scales ~linearly in prompt tokens, so
                # the saved share is the reused fraction scaled onto
                # the measured suffix prefill (a documented estimate)
                pf_ms = (t_first - t_rx1) * 1e3
                req.prefill_saved_ms = pf_ms * matched[i] \
                    / int(t0s_suf[i])
            if req.trace is not None:
                # retroactive spans from the stamps above: queue covers
                # dispatch + bucket dwell, prefill the forward + commit
                req.trace.add("queue", req.t_submit, t_start,
                              replica=r.index)
                if rx is not None:
                    req.trace.add("radix_lookup", t_rx0, t_rx1,
                                  replica=r.index,
                                  hit_tokens=matched[i])
                req.trace.add("prefill", t_start, t_first,
                              replica=r.index, slot=req.slot,
                              kv_blocks=req.kv_blocks,
                              bucket=list(req.bucket),
                              mates=[m for m in mates if m != req.id])
            if mgr.consume(req.slot):
                # max_new_tokens == 1: done at prefill, never decodes
                r.finish(req, [int(first[i])])
            else:
                r.decode.hand_off(_Handoff(req, req.slot,
                                           int(first[i])))
        telemetry.count("serving.admitted", len(group))
        return True


class DecodeLane:
    """Slot-set advancement, one thread per replica: adopt handoffs,
    tick every in-flight slot, evict finished requests (returning their
    KV blocks to the pool)."""

    def __init__(self, replica, poll_s=0.005):
        self.r = replica
        self.poll_s = float(poll_s)
        self._handoffs = deque()
        self._hand_lock = _san.wrap_lock(
            threading.Lock(), "lanes.DecodeLane._hand_lock")
        self._seqs = {}       # slot -> (request, [generated tokens])
        self._wake = threading.Event()   # set on hand_off: adopt now
        self._stop = threading.Event()
        self._thread = None
        self.error = None

    def hand_off(self, h):
        with self._hand_lock:
            self._handoffs.append(h)
        self._wake.set()

    def pending(self):
        with self._hand_lock:
            return len(self._handoffs) + len(self._seqs)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop,
                name=f"mxt-decode-r{self.r.index}", daemon=True)
            self._thread.start()

    def request_stop(self):
        self._stop.set()

    def join(self):
        """Join the lane thread; a captured lane-machinery error is
        re-raised here — the lane's materialization point."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self.error is not None:
            raise self.error

    def alive(self):
        """Lane-thread liveness (the /healthz signal)."""
        return self._thread is not None and self._thread.is_alive()

    def snapshot(self):
        """In-flight view for the /requests table: handoffs not yet
        adopted + decoding slots, host-side bookkeeping only."""
        rows = []
        with self._hand_lock:
            handoffs = list(self._handoffs)
            seqs = dict(self._seqs)
        for h in handoffs:
            rows.append({"request_id": h.req.id, "state": "handoff",
                         "slot": h.slot, "replica": self.r.index})
        for slot, (req, tokens) in seqs.items():
            rows.append({"request_id": req.id, "state": "decoding",
                         "slot": slot, "replica": self.r.index,
                         "tokens_done": len(tokens),
                         "max_new_tokens": req.max_new_tokens})
        return rows

    def _loop(self):
        # per-request failures are handled inside _tick; this catches
        # lane-machinery bugs so the thread never dies silently
        try:
            self._run()
        except Exception as exc:
            self.error = exc
            tracing.incident("lane_thread_error",
                             context={"replica": self.r.index,
                                      "lane": "decode",
                                      "error": repr(exc)})

    def _run(self):
        spec = self.r.spec_k > 0 and self.r.draft is not None
        while True:
            self._adopt()
            with self._hand_lock:
                busy = bool(self._seqs)
            if busy:
                self._tick_spec() if spec else self._tick()
            elif self._stop.is_set():
                if not self.pending():
                    break
            else:
                self._wake.wait(self.poll_s)
                self._wake.clear()

    def _adopt(self):
        """Pull every pending handoff into this lane's slot set.  The
        KV rows are already in the request's blocks (the prefill lane
        committed them before handing off), so adoption is pure
        bookkeeping — decode only ever advances slots it has adopted,
        never a slot whose commit is still in flight."""
        while True:
            with self._hand_lock:
                if not self._handoffs:
                    return
                h = self._handoffs.popleft()
            h.req.t_handoff = time.perf_counter()
            hand_ms = (h.req.t_handoff - h.req.t_first) * 1e3
            telemetry.hist("serving.handoff_ms", hand_ms)
            telemetry.hist(f"serving.handoff_ms|replica={self.r.index}",
                           hand_ms)
            if h.req.trace is not None:
                h.req.trace.add("handoff", h.req.t_first,
                                h.req.t_handoff, replica=self.r.index,
                                slot=h.slot)
            with self._hand_lock:
                self._seqs[h.slot] = (h.req, [h.first])

    def _tick(self):
        r = self.r
        with self._hand_lock:
            active = sorted(self._seqs)
        t0 = time.perf_counter()
        try:
            toks = r.engine.step(active)
        except Exception as exc:
            for slot in active:
                with self._hand_lock:
                    req, _ = self._seqs.pop(slot)
                r.mgr.evict(slot)
                r.engine.clear_slot(slot)
                req.future.set_exception(exc)
                r.fail(req, exc, lane="decode")
            r.capacity_evt.set()
            tracing.incident("replica_exception",
                             context={"replica": r.index,
                                      "lane": "decode",
                                      "error": repr(exc)})
            return
        t1 = time.perf_counter()
        r.batches += 1
        telemetry.hist("serving.batch_size", len(active))
        telemetry.gauge("serving.kv_blocks_in_use",
                        r.mgr.allocator.blocks_in_use)
        # retroactive capacity accounting from the stamps above: the
        # busy interval, batch occupancy, and pool pressure per tick.
        # Gated on is_enabled() so the argument expressions impose no
        # attribute contract (or cost) on duck-typed engines/managers
        # when capacity accounting is off.
        if capacity.is_enabled():
            capacity.note_tick(r.index, len(active),
                               getattr(r.engine, "num_slots", len(active)),
                               t0, t1)
            capacity.note_kv(r.index, r.mgr.allocator.free_blocks,
                             r.mgr.num_blocks)
        step_idx = r.engine.steps
        for slot in active:
            r.mgr.advance(slot)   # the step wrote K/V at slot's pos
            with self._hand_lock:
                req, tokens = self._seqs[slot]
            tokens.append(int(toks[slot]))
            if req.trace is not None:
                # one span per traced slot per tick: the per-request
                # decode slice (cost: one dict append — the tracing
                # A/B lane in benchmark/serving_latency.py bounds it)
                req.trace.add("decode.step", t0, t1, step=step_idx,
                              batch=len(active), replica=r.index,
                              slot=slot)
            if r.mgr.consume(slot):
                with self._hand_lock:
                    del self._seqs[slot]
                r.finish(req, tokens)

    def _tick_spec(self):
        """Speculative tick: k sequential DRAFT steps propose a window,
        ONE target verify scores all k+1 positions, and greedy
        token-exact acceptance commits the matched prefix plus (below
        full acceptance) the target's correction token — bit-identical
        output to plain decode (every emitted token is a target argmax
        given previously emitted tokens), at one target forward per
        up-to-k tokens.

        Rollback is host-side only: the manager's cursor advances by
        the full window then truncates to the accepted position; the
        rejected rows' K/V sits masked in the pool until the next
        window overwrites it (kv_cache.truncate's stale-row
        contract)."""
        r = self.r
        k = r.spec_k
        with self._hand_lock:
            active = sorted(self._seqs)
        t0 = time.perf_counter()
        proposals = np.zeros((r.engine.num_slots, k), np.int32)
        try:
            for j in range(k):
                # draft mirrors auto-advance, so step j+1 is
                # conditioned on the draft's own proposal j
                proposals[:, j] = r.draft.step(active)
            t_draft = time.perf_counter()
            pos0 = r.engine.positions()
            out = r.engine.verify(proposals)
        except Exception as exc:
            for slot in active:
                with self._hand_lock:
                    req, _ = self._seqs.pop(slot)
                r.mgr.evict(slot)
                r.engine.clear_slot(slot)
                r.draft.clear_slot(slot)
                req.future.set_exception(exc)
                r.fail(req, exc, lane="decode")
            r.capacity_evt.set()
            tracing.incident("replica_exception",
                             context={"replica": r.index,
                                      "lane": "decode",
                                      "error": repr(exc)})
            return
        t1 = time.perf_counter()
        r.batches += 1
        telemetry.hist("serving.batch_size", len(active))
        telemetry.gauge("serving.kv_blocks_in_use",
                        r.mgr.allocator.blocks_in_use)
        if capacity.is_enabled():
            capacity.note_tick(r.index, len(active),
                               getattr(r.engine, "num_slots", len(active)),
                               t0, t1)
            capacity.note_kv(r.index, r.mgr.allocator.free_blocks,
                             r.mgr.num_blocks)
        accepted_this_tick = 0
        step_idx = r.engine.steps
        for slot in active:
            d, g = proposals[slot], out[slot]
            m = 0
            while m < k and d[m] == g[m]:
                m += 1
            st = r.mgr.state(slot)
            # accepted = matched drafts + the target's own next token,
            # capped at k (on full acceptance the bonus token is NOT
            # taken: the draft's cache only holds rows for [last,
            # d1..d_{k-1}], so emitting g_{k+1} would leave the draft a
            # KV row short and poison every later proposal) and clamped
            # to the tokens still owed (never over-emit)
            acc = min(m + 1, k, int(st.remaining))
            adv = min(k + 1, int(st.reserved) - int(st.pos))
            r.mgr.advance_n(slot, adv)
            r.mgr.truncate(slot, int(pos0[slot]) + acc)
            last = int(g[acc - 1])
            r.engine.set_mirror(slot, last, int(pos0[slot]) + acc)
            r.draft.set_mirror(slot, last, int(pos0[slot]) + acc)
            with self._hand_lock:
                req, tokens = self._seqs[slot]
            tokens.extend(int(t) for t in g[:acc])
            got = min(m, acc)
            req.draft_tokens += k
            req.accepted_tokens += got
            r.draft_tokens += k
            r.accepted_tokens += got
            accepted_this_tick += got
            telemetry.count("serving.accepted_tokens", got)
            if req.trace is not None:
                req.trace.add("draft", t0, t_draft, step=step_idx,
                              k=k, replica=r.index, slot=slot)
                req.trace.add("verify", t_draft, t1, step=step_idx,
                              accepted=acc, replica=r.index, slot=slot)
            done = False
            for _ in range(acc):
                if r.mgr.consume(slot):
                    done = True
            if done:
                with self._hand_lock:
                    del self._seqs[slot]
                r.finish(req, tokens)
        telemetry.count("serving.draft_tokens", k * len(active))
        capacity.note_spec(r.index, k * len(active), accepted_this_tick)
        if r.draft_tokens:
            telemetry.gauge("serving.accept_rate",
                            round(r.accepted_tokens
                                  / r.draft_tokens, 4))


class Replica:
    """One model replica: engine + paged-KV manager + lane pair over
    one (tp) submesh, fed by a bounded internal queue."""

    def __init__(self, net, policy, index=0, mesh=None,
                 partition_rules=None, num_slots=4, int8=False,
                 block_size=16, num_blocks=None, queue_capacity=64,
                 max_prefill_tokens=None, summary_every=32, slo=None,
                 draft_net=None, spec_k=0, radix_cache=False,
                 prefix_cache_tokens=None):
        from .generative import LlamaServingEngine

        self.index = int(index)
        self.policy = policy
        self.spec_k = int(spec_k) if draft_net is not None else 0
        self.engine = LlamaServingEngine(
            net, max_len=policy.max_length, num_slots=num_slots,
            int8=int8, kv_mode="paged", block_size=block_size,
            num_blocks=num_blocks, mesh=mesh,
            partition_rules=partition_rules, replica_id=self.index,
            spec_k=self.spec_k)
        self.draft = None
        if self.spec_k > 0:
            # the draft runs the r8 slot-ledger engine: fixed per-slot
            # cache rows, no block bookkeeping to keep consistent with
            # the target's pool — its k sequential steps are cheap by
            # model size, not by storage cleverness
            self.draft = LlamaServingEngine(
                draft_net, max_len=policy.max_length,
                num_slots=num_slots, int8=int8, kv_mode="slots",
                mesh=mesh, partition_rules=partition_rules,
                replica_id=self.index)
        self.mgr = PagedKVCacheManager(
            num_slots, policy.max_length,
            num_blocks=self.engine.num_blocks,
            block_size=self.engine.block_size)
        self.radix = None
        if radix_cache:
            from .radix import RadixPrefixCache
            cap = int(prefix_cache_tokens
                      if prefix_cache_tokens is not None
                      else self.engine.num_blocks
                      * self.engine.block_size // 2)
            self.radix = RadixPrefixCache(self.mgr.allocator,
                                          self.engine.block_size, cap)
            self.mgr.prefix_cache = self.radix
        self.draft_tokens = 0
        self.accepted_tokens = 0
        self.queue = RequestQueue(queue_capacity)
        self.max_prefill_tokens = int(max_prefill_tokens or
                                      policy.max_batch
                                      * policy.max_length)
        self.summary_every = int(summary_every)
        self.prefill = PrefillLane(self)
        self.decode = DecodeLane(self)
        self.capacity_evt = threading.Event()  # set on evict: re-admit
        self.slo = slo   # shared SLOTracker (metrics.py) or None
        self.completed = 0
        self.failed = 0
        self.batches = 0

    # -- dispatcher-facing ----------------------------------------------------
    def load(self):
        """Routing weight: tokens reserved in the KV pool plus tokens
        waiting in the internal queue."""
        queued = self.queue.queued_tokens(
            lambda r: len(r.prompt_ids) + r.max_new_tokens)
        return self.mgr.reserved_tokens() + queued

    def offer(self, req):
        ok = self.queue.offer(req)
        if ok:
            # accepted offers only: a shed request never joins the
            # arrival process the λ estimator models
            capacity.note_arrival(self.index, t=req.t_submit)
        return ok

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        self.prefill.start()
        self.decode.start()

    def stop(self, drain=True):
        """Drain order matters: prefill first (with decode still live,
        so draining admissions can wait for blocks decode will free),
        then decode finishes the in-flight slot set."""
        self.queue.close()
        self.prefill.request_stop(drain)
        self.prefill.join()
        self.decode.request_stop()
        self.decode.join()
        for req in self.queue.take_group(lambda r: 0, 1 << 30):
            req.future.set_exception(
                ServerClosedError("server stopped before execution"))

    # -- completion -----------------------------------------------------------
    def finish(self, req, tokens):
        self.mgr.evict(req.slot)
        self.engine.clear_slot(req.slot)
        if self.draft is not None:
            self.draft.clear_slot(req.slot)
        self.capacity_evt.set()
        req.t_done = time.perf_counter()
        req.done_step = self.engine.steps
        n = req.max_new_tokens
        req.future.set_result(np.concatenate(
            [np.asarray(req.prompt_ids, np.int32),
             np.asarray(tokens[:n], np.int32)]))
        self.completed += 1
        telemetry.count("serving.completed")
        telemetry.count(f"serving.completed|replica={self.index}")
        capacity.note_completion(self.index, t=req.t_done)
        lane = "decode" if req.t_handoff is not None else "prefill"
        rec = req.record(lane=lane)
        tag = f"|replica={self.index}"
        if rec["queue_wait_ms"] is not None:
            telemetry.hist("serving.queue_wait_ms", rec["queue_wait_ms"])
            telemetry.hist("serving.queue_wait_ms" + tag,
                           rec["queue_wait_ms"])
        if rec["total_ms"] is not None:
            telemetry.hist("serving.total_ms", rec["total_ms"])
            telemetry.hist("serving.total_ms" + tag, rec["total_ms"])
        if rec.get("ttft_ms") is not None:
            telemetry.hist("serving.ttft_ms", rec["ttft_ms"])
            telemetry.hist("serving.ttft_ms" + tag, rec["ttft_ms"])
        if rec.get("tpot_ms") is not None:
            telemetry.hist("serving.tpot_ms", rec["tpot_ms"])
            telemetry.hist("serving.tpot_ms" + tag, rec["tpot_ms"])
        if self.slo is not None:
            rec["slo_met"] = self.slo.observe(
                tenant=req.tenant, ttft_ms=rec.get("ttft_ms"),
                tpot_ms=rec.get("tpot_ms"))
        telemetry.emit(rec)
        if req.trace is not None:
            req.trace.event("evict", replica=self.index, slot=req.slot)
            tracing.finish(req.trace, status="ok", replica=self.index,
                           lane=lane, request_id=req.id)
        if self.summary_every and self.completed % self.summary_every == 0:
            self.emit_summary()

    def fail(self, req, exc, lane):
        """Failure-path accounting: the ``status="error"`` request
        record (tagged replica + lane — the eviction/rejection paths
        used to drop both), the failed counters, and the trace seal."""
        self.failed += 1
        telemetry.count("serving.failed")
        telemetry.count(f"serving.failed|replica={self.index}")
        req.t_done = time.perf_counter()
        telemetry.emit(req.record(lane=lane, status="error",
                                  error=repr(exc)))
        if req.trace is not None:
            tracing.finish(req.trace, status="error",
                           replica=self.index, lane=lane,
                           error=repr(exc), request_id=req.id)

    def emit_summary(self):
        rec = {
            "record": "serving.latency",
            "replica": self.index,
            "completed": self.completed,
            "failed": self.failed,
            "batches": self.batches,
            "queue_wait_ms": telemetry.hist_summary("serving.queue_wait_ms"),
            "total_ms": telemetry.hist_summary("serving.total_ms"),
            "ttft_ms": telemetry.hist_summary("serving.ttft_ms"),
            "handoff_ms": telemetry.hist_summary("serving.handoff_ms"),
            "batch_size": telemetry.hist_summary("serving.batch_size"),
            "kv_cache": self.mgr.stats(),
        }
        # the summary path already paid for stats(): feed the pool's
        # fragmentation figure to the capacity trend estimator here
        capacity.note_kv(self.index,
                         self.mgr.allocator.free_blocks,
                         self.mgr.num_blocks,
                         fragmentation=rec["kv_cache"].get(
                             "fragmentation"))
        cap_view = capacity.snapshot(self.index)
        if cap_view is not None:
            rec["capacity"] = cap_view
        if self.draft is not None:
            rec["speculative"] = {
                "k": self.spec_k,
                "draft_tokens": self.draft_tokens,
                "accepted_tokens": self.accepted_tokens,
                "accept_rate": round(self.accepted_tokens
                                     / self.draft_tokens, 4)
                if self.draft_tokens else None,
            }
        if self.radix is not None:
            rec["radix_cache"] = self.radix.stats()
        telemetry.emit(rec)


class ReplicaDispatcher:
    """Routes the front queue to the least-loaded replica.

    One thread pops the FIFO head and offers it to the replica with the
    smallest :meth:`Replica.load` that has internal queue space; if all
    replica queues are full the head is held (client backpressure
    already happened at the front queue's bounded ``put``)."""

    def __init__(self, queue, replicas, poll_s=0.005):
        self.queue = queue
        self.replicas = list(replicas)
        self.poll_s = float(poll_s)
        self._held = None
        self._stop = threading.Event()
        self._drain = True
        self._thread = None
        self.error = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="mxt-dispatch",
                                            daemon=True)
            self._thread.start()

    def stop(self, drain=True):
        self._drain = drain
        self._stop.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self.error is not None:
            raise self.error
        leftovers = ([self._held] if self._held is not None else []) \
            + self.queue.take_group(lambda r: 0, 1 << 30)
        self._held = None
        for req in leftovers:
            if drain:
                while not self._route(req):
                    time.sleep(self.poll_s)
            else:
                req.future.set_exception(
                    ServerClosedError("server stopped before execution"))

    def _route(self, req):
        for rep in sorted(self.replicas, key=lambda r: r.load()):
            if rep.offer(req):
                return True
        return False

    def _loop(self):
        # catches dispatcher bugs so the routing thread never dies
        # silently; re-raised at stop()
        try:
            self._run()
        except Exception as exc:
            self.error = exc
            tracing.incident("dispatcher_thread_error",
                             context={"error": repr(exc)})

    def _run(self):
        while not self._stop.is_set():
            if self._held is None:
                group = self.queue.take_group(lambda r: 0, 1)
                if not group:
                    self.queue.wait_for_item(self.poll_s)
                    continue
                self._held = group[0]
            if self._route(self._held):
                self._held = None
            else:
                time.sleep(self.poll_s)
