"""Request queue and continuous-batching scheduler loop.

The dataflow: client threads ``put()`` requests into a bounded
:class:`RequestQueue` (full → :class:`ServerOverloadedError`, the
backpressure contract); one scheduler thread repeatedly takes the
FIFO-head-compatible group of pending requests (same length bucket, up
to the batch-bucket ceiling), pads them into one compiled-signature
shape (``bucketing.pad_batch``), runs the model, and demultiplexes the
batch output back to per-request futures.

Host-sync discipline: the ONE place this module blocks on device
results is :func:`_materialize` — by design, at the batch boundary,
after the whole batch was dispatched.  ``tools/lint`` exempts that def
from the eager T1 warning (``MATERIALIZE_DEFS`` in tools/lint/rules.py,
mirroring the async-checkpoint ``ticket.result()`` treatment); syncs
added anywhere else in the serving path still get flagged.

Every completed request emits a ``serving.request`` JSONL record and
feeds the rolling latency histograms; every ``summary_every``
completions the scheduler emits a ``serving.latency`` summary record
with p50/p90/p99 over the recent window (telemetry.hist_summary).
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import sanitizer as _san
from .. import telemetry
from ..telemetry import tracing
from .bucketing import pad_batch
from .protocol import ServerClosedError, ServerOverloadedError

__all__ = ["RequestQueue", "BatchScheduler"]


def _materialize(arrays):
    """THE designated result-materialization point: batch outputs →
    host numpy, one sync per batch after full dispatch.  Keep every
    device->host wait in the serving path inside this function — it is
    the serving scheduler's lint-sanctioned sync site."""
    out = []
    for a in arrays:
        if hasattr(a, "asnumpy"):
            out.append(a.asnumpy())
        else:
            out.append(np.asarray(a))
    return out


class RequestQueue:
    """Thread-safe bounded FIFO with bucket-aware group take."""

    def __init__(self, capacity=64):
        self.capacity = int(capacity)
        self._items = []
        self._cond = _san.wrap_lock(threading.Condition(),
                                    "scheduler.RequestQueue._cond")
        self._closed = False
        self._rejected = 0

    def __len__(self):
        with self._cond:
            return len(self._items)

    @property
    def rejected(self):
        with self._cond:
            return self._rejected

    def queued_tokens(self, weigh):
        """Sum ``weigh(req)`` over the queued requests under the lock —
        the dispatcher's load probe, so callers never reach into
        ``_items`` bare."""
        with self._cond:
            return sum(weigh(r) for r in self._items)

    def put(self, req):
        with self._cond:
            if self._closed:
                raise ServerClosedError("server is not accepting requests")
            if len(self._items) >= self.capacity:
                self._rejected += 1
                telemetry.count("serving.rejected")
                raise ServerOverloadedError(
                    f"request queue full ({self.capacity} pending); "
                    "retry with backoff")
            self._items.append(req)
            self._cond.notify_all()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def wait_for_item(self, timeout):
        """Block until an item is queued (True) or timeout/closed."""
        with self._cond:
            if not self._items:
                self._cond.wait(timeout)
            return bool(self._items)

    def offer(self, req):
        """Non-raising ``put``: False when full/closed instead of an
        exception, and never counted as a rejection — the dispatcher's
        primitive for routing an ALREADY-accepted request to a replica
        queue (the client-facing backpressure happened at the front
        queue's ``put``)."""
        with self._cond:
            if self._closed or len(self._items) >= self.capacity:
                return False
            self._items.append(req)
            self._cond.notify_all()
            return True

    def take_group(self, key_fn, max_n):
        """Pop the FIFO head plus every queued request sharing its
        ``key_fn`` value (the length bucket), up to ``max_n``, keeping
        everything else in order.  Empty queue → []."""
        with self._cond:
            if not self._items:
                return []
            head_key = key_fn(self._items[0])
            taken, rest = [], []
            for r in self._items:
                if len(taken) < max_n and key_fn(r) == head_key:
                    taken.append(r)
                else:
                    rest.append(r)
            self._items = rest
            return taken

    def take_batch(self, key_fn, max_n, accept):
        """Like :meth:`take_group`, but each candidate must also pass
        ``accept(req)`` — the prefill lane's admission gate (cumulative
        KV block budget).  Stops at the FIRST head-bucket request the
        gate refuses, so admission stays FIFO within the bucket instead
        of starving a big request behind small ones."""
        with self._cond:
            if not self._items:
                return []
            head_key = key_fn(self._items[0])
            taken, rest = [], []
            gate_shut = False
            for r in self._items:
                if (not gate_shut and len(taken) < max_n
                        and key_fn(r) == head_key):
                    if accept(r):
                        taken.append(r)
                        continue
                    gate_shut = True
                rest.append(r)
            self._items = rest
            return taken


class BatchScheduler:
    """The dynamic-batching loop for stateless (single forward) models.

    ``runner(batch_inputs)`` takes a dict name → stacked numpy array of
    one padded bucket shape and returns the model outputs (NDArrays or
    arrays); the server layer builds it around a Predictor or a gluon
    block.  ``output_length_axis`` (optional) names the per-example
    output axis to trim back to the request's true length at demux —
    None for pooled outputs (classifiers) whose shape has no length
    axis.
    """

    def __init__(self, runner, policy, queue, length_axis=0,
                 output_length_axis=None, batch_window_ms=2.0,
                 summary_every=32, poll_s=0.05):
        self.runner = runner
        self.policy = policy
        self.queue = queue
        self.length_axis = int(length_axis)
        self.output_length_axis = output_length_axis
        self.batch_window_s = float(batch_window_ms) * 1e-3
        self.summary_every = int(summary_every)
        self.poll_s = float(poll_s)
        self.batches = 0
        self.completed = 0
        self.failed = 0
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="mxt-serving-sched",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain=True):
        """Stop the loop; with ``drain`` (default) queued requests are
        served first, otherwise they fail with ServerClosedError."""
        self._stop.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        leftovers = self.queue.take_group(lambda r: 0, 1 << 30)
        if drain and leftovers:
            for group in self._regroup(leftovers):
                self._serve_batch(group)
        else:
            for r in leftovers:
                r.future.set_exception(
                    ServerClosedError("server stopped before execution"))

    def _regroup(self, reqs):
        groups = {}
        for r in reqs:
            groups.setdefault(self._bucket_key(r), []).append(r)
        return [g[i:i + self.policy.max_batch]
                for g in groups.values()
                for i in range(0, len(g), self.policy.max_batch)]

    # -- the loop -------------------------------------------------------------
    def _bucket_key(self, req):
        return self.policy.length_bucket(req.length)

    def _loop(self):
        while not self._stop.is_set():
            if not self.queue.wait_for_item(self.poll_s):
                continue
            if self.batch_window_s > 0:
                # dwell briefly so concurrent submitters land in ONE
                # batch instead of head-of-line singletons
                time.sleep(self.batch_window_s)
            group = self.queue.take_group(self._bucket_key,
                                          self.policy.max_batch)
            if group:
                self._serve_batch(group)

    def _serve_batch(self, group):
        t_start = time.perf_counter()
        lb = self._bucket_key(group[0])
        bb = self.policy.batch_bucket(len(group))
        for r in group:
            r.t_start = t_start
            r.bucket = (bb, lb)
            r.batch_size = len(group)
        try:
            names = list(group[0].inputs)
            batch = {
                name: pad_batch([r.inputs[name] for r in group], bb, lb,
                                axis=self.length_axis)
                for name in names}
            with telemetry.span("serving.batch",
                                {"batch": bb, "length": lb}):
                outs = self.runner(batch)
            outs = _materialize(outs if isinstance(outs, (list, tuple))
                                else [outs])
        except Exception as exc:
            self.failed += len(group)
            telemetry.count("serving.failed", len(group))
            for r in group:
                r.t_done = time.perf_counter()
                r.future.set_exception(exc)
                telemetry.emit(r.record(lane="batch", status="error",
                                        error=repr(exc)))
                if r.trace is not None:
                    tracing.finish(r.trace, status="error",
                                   lane="batch", error=repr(exc),
                                   request_id=r.id)
            return
        self.batches += 1
        t_done = time.perf_counter()
        telemetry.count("serving.batches")
        telemetry.hist("serving.batch_size", len(group))
        for i, r in enumerate(group):
            r.t_done = t_done
            if r.trace is not None:
                r.trace.add("queue", r.t_submit, t_start)
                r.trace.add("batch", t_start, t_done,
                            bucket=list(r.bucket),
                            batch=len(group))
            r.future.set_result(self._demux(outs, i, r.length))
            self._account(r)

    def _demux(self, outs, i, length):
        picked = []
        for o in outs:
            row = o[i]
            if self.output_length_axis is not None:
                row = np.take(row, np.arange(length),
                              axis=self.output_length_axis)
            picked.append(row)
        return picked if len(picked) > 1 else picked[0]

    def _account(self, req):
        """Per-request telemetry: histograms + JSONL record + rolling
        summary every ``summary_every`` completions."""
        self.completed += 1
        telemetry.count("serving.completed")
        rec = req.record(lane="batch")
        if req.trace is not None:
            tracing.finish(req.trace, status="ok", lane="batch",
                           request_id=req.id)
        if rec["queue_wait_ms"] is not None:
            telemetry.hist("serving.queue_wait_ms", rec["queue_wait_ms"])
        if rec["total_ms"] is not None:
            telemetry.hist("serving.total_ms", rec["total_ms"])
        if rec.get("ttft_ms") is not None:
            telemetry.hist("serving.ttft_ms", rec["ttft_ms"])
        telemetry.emit(rec)
        if self.summary_every and self.completed % self.summary_every == 0:
            self.emit_summary()

    def emit_summary(self):
        """Emit the rolling ``serving.latency`` percentile record."""
        telemetry.emit({
            "record": "serving.latency",
            "completed": self.completed,
            "failed": self.failed,
            "batches": self.batches,
            "rejected": self.queue.rejected,
            "queue_wait_ms": telemetry.hist_summary("serving.queue_wait_ms"),
            "total_ms": telemetry.hist_summary("serving.total_ms"),
            "ttft_ms": telemetry.hist_summary("serving.ttft_ms"),
            "batch_size": telemetry.hist_summary("serving.batch_size"),
        })
