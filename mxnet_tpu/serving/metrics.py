"""Live metrics endpoint + SLO goodput accounting for the servers.

Three pieces, all host-side (the HTTP thread never touches a device
buffer — it renders telemetry snapshots and ledger stats that the
serving threads already maintain):

* :func:`prometheus_text` — the telemetry snapshot rendered as
  Prometheus text exposition (v0.0.4).  Since r13 the renderer lives in
  ``telemetry.promtext`` (shared with the training-side
  ``telemetry.fleet.MetricsEndpoint``) and is re-exported here
  unchanged: dotted names sanitize to ``mxt_*`` families, ``|key=value``
  suffixes carry labels, histograms render as summaries.
* :class:`MetricsServer` — a stdlib ``http.server`` daemon thread bound
  to an owner server, exposing ``/metrics`` (the text above plus the
  owner's live gauges), ``/healthz`` (per-replica lane liveness, queue
  depths, KV occupancy/fragmentation; HTTP 503 when degraded, but
  ``saturated`` — all lanes alive, capacity ρ past threshold — stays
  HTTP 200) and
  ``/requests`` (the in-flight request table).  Enabled per-server via
  ``ServerConfig(http_port=...)`` (0 = ephemeral port, see
  ``server.metrics_url``) — scrape while the server runs.
* :class:`SLOTracker` — per-tenant TTFT/TPOT targets with **goodput**
  (fraction of requests meeting their SLO) over both a rolling window
  and the all-time stream.  The serving completion paths call
  :meth:`SLOTracker.observe`; results land in ``server.stats()["slo"]``
  and as ``mxt_serving_goodput{tenant=...}`` gauges on ``/metrics``.

Schema details in docs/observability.md.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..telemetry.promtext import (  # noqa: F401  (re-exported; hoisted r13)
    _NAME_RE, _QUANTILES, _fmt_labels, _fmt_value, _prom_name,
    _split_labels, prometheus_text,
)

__all__ = ["prometheus_text", "MetricsServer", "SLOTracker"]


# -- SLO goodput -------------------------------------------------------------

class SLOTracker:
    """Per-tenant TTFT/TPOT targets and rolling goodput.

    ``targets`` maps tenant name → ``{"ttft_ms": x, "tpot_ms": y}``
    (either key optional); the ``"default"`` entry covers tenants
    without their own row.  A flat ``{"ttft_ms": ..}`` dict is accepted
    as shorthand for ``{"default": ...}``.  ``observe`` is called once
    per completed request and judges only the metrics the target
    actually names (a 1-token request has no TPOT; it is not penalized
    for it)."""

    def __init__(self, targets, window=256):
        targets = dict(targets or {})
        if targets and not any(isinstance(v, dict)
                               for v in targets.values()):
            targets = {"default": targets}
        self.targets = targets
        self.window = int(window)
        self._lock = threading.Lock()
        self._tenants = {}  # tenant -> {"window": deque, "met": n, "total": n}

    def target_for(self, tenant=None):
        """The SLO row applying to ``tenant`` (None when neither the
        tenant nor ``"default"`` is configured)."""
        return self.targets.get(tenant or "default",
                                self.targets.get("default"))

    def observe(self, tenant=None, ttft_ms=None, tpot_ms=None):
        """Judge one completed request against its tenant's targets.
        Returns True/False (met / missed), or None when no target
        applies (nothing is recorded)."""
        target = self.target_for(tenant)
        if target is None:
            return None
        met, judged = True, False
        t = target.get("ttft_ms")
        if t is not None and ttft_ms is not None:
            judged = True
            met = met and ttft_ms <= t
        t = target.get("tpot_ms")
        if t is not None and tpot_ms is not None:
            judged = True
            met = met and tpot_ms <= t
        if not judged:
            return None
        key = tenant or "default"
        with self._lock:
            row = self._tenants.get(key)
            if row is None:
                row = self._tenants[key] = {
                    "window": deque(maxlen=self.window),
                    "met": 0, "total": 0}
            row["window"].append(1 if met else 0)
            row["total"] += 1
            row["met"] += 1 if met else 0
        return met

    def goodput(self, tenant=None):
        """Rolling-window goodput fraction for ``tenant`` (None before
        any observation)."""
        with self._lock:
            row = self._tenants.get(tenant or "default")
            if row is None or not row["window"]:
                return None
            return sum(row["window"]) / len(row["window"])

    def snapshot(self):
        """``stats()``-shaped summary: targets + per-tenant goodput
        over the rolling window and the all-time stream."""
        with self._lock:
            tenants = {
                t: {
                    "total": row["total"],
                    "met": row["met"],
                    "goodput": row["met"] / row["total"]
                    if row["total"] else None,
                    "window": len(row["window"]),
                    "window_goodput": sum(row["window"]) / len(row["window"])
                    if row["window"] else None,
                }
                for t, row in self._tenants.items()}
        return {"targets": self.targets, "window": self.window,
                "tenants": tenants}


# -- the HTTP endpoint thread ------------------------------------------------

def _make_handler(ms):
    class _Handler(BaseHTTPRequestHandler):
        server_version = "mxt-serving"

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    code = 200
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                    body = ms.render_metrics().encode("utf-8")
                elif path == "/healthz":
                    health = ms.owner.health()
                    # "saturated" is degraded-but-alive: lanes are all
                    # serving, capacity ρ is just past threshold — a
                    # 503 here would make the orchestrator restart a
                    # busy replica and shed the very capacity it needs
                    code = (200 if health.get("status")
                            in ("ok", "saturated") else 503)
                    ctype = "application/json"
                    body = json.dumps(health, indent=2,
                                      default=str).encode("utf-8")
                elif path == "/requests":
                    code = 200
                    ctype = "application/json"
                    body = json.dumps(ms.owner.in_flight(), indent=2,
                                      default=str).encode("utf-8")
                else:
                    code, ctype = 404, "text/plain"
                    body = b"not found; endpoints: /metrics /healthz " \
                           b"/requests"
            except Exception as exc:  # scrape errors never kill serving
                code, ctype, body = 500, "text/plain", repr(exc).encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):   # scrapes stay off stderr
            pass

    return _Handler


class MetricsServer:
    """The per-server scrape endpoint: one ``ThreadingHTTPServer`` on a
    daemon thread.  ``owner`` is the serving server — it must provide
    ``health()`` and ``in_flight()`` (both host-side snapshots) and may
    provide ``metrics_gauges()`` for extra live gauges on ``/metrics``
    and ``slo`` (an :class:`SLOTracker`) for goodput gauges."""

    def __init__(self, owner, host="127.0.0.1", port=0):
        self.owner = owner
        self.host = host
        self._requested_port = int(port)
        self._httpd = None
        self._thread = None

    @property
    def port(self):
        """The bound port (resolves ``port=0`` to the ephemeral one)."""
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}" if self._httpd else None

    def render_metrics(self):
        extra = {}
        gauges_fn = getattr(self.owner, "metrics_gauges", None)
        if gauges_fn is not None:
            extra.update(gauges_fn())
        slo = getattr(self.owner, "slo", None)
        if slo is not None:
            for tenant, row in slo.snapshot()["tenants"].items():
                if row["window_goodput"] is not None:
                    extra[f"serving.goodput|tenant={tenant}"] = \
                        row["window_goodput"]
        return prometheus_text(extra_gauges=extra)

    def start(self):
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), _make_handler(self))
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="mxt-metrics", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join()
        self._httpd = self._thread = None
