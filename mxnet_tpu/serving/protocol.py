"""Request/response protocol for the serving layer.

Reference: the C predict API (``c_predict_api.h``, SURVEY §3.5) is a
single-session, caller-threaded surface — one Predictor, one request at
a time.  The serving subsystem puts a queue/scheduler in front of it,
so the protocol objects here carry what the C API's stack frame used to
carry implicitly: identity, timing, and a completion handle.

A :class:`Request` is one unit of admitted work.  Its ``future`` (a
``concurrent.futures.Future``) is the caller's completion handle —
``future.result(timeout)`` in client glue is the intended wait point
(the same contract as async-checkpoint tickets; see docs/lint.md on why
``.result()`` is legal in eager glue but an error inside traced code).

Backpressure is explicit: a full queue raises
:class:`ServerOverloadedError` at submit time instead of buying
unbounded latency.  Clients treat it like HTTP 503 — back off and
retry.
"""
from __future__ import annotations

import itertools
import time
from concurrent.futures import Future

from ..base import MXNetError

__all__ = ["Request", "ServerOverloadedError", "ServerClosedError"]


class ServerOverloadedError(MXNetError):
    """The bounded request queue is full: the server sheds load at
    admission instead of queueing into unbounded latency.  Retry with
    backoff, or raise ``queue_capacity``."""


class ServerClosedError(MXNetError):
    """Submit after ``stop()`` (or before ``start()``)."""


_ids = itertools.count(1)


class Request:
    """One in-flight inference request.

    ``inputs`` maps input name → host numpy array for ONE example —
    the length-bucketed axis is ``length_axis`` (batch dim added by the
    scheduler).  Generative requests carry ``prompt_ids`` (1-D int32)
    and ``max_new_tokens`` instead.

    Timing fields are filled in as the request moves through the
    pipeline and land verbatim in the per-request telemetry record:
    ``t_submit`` → ``t_start`` (dequeued into a batch; the delta is
    ``queue_wait_ms``) → ``t_first`` (generative: first token emitted;
    delta from submit is ``ttft_ms``) → ``t_done``.
    """

    __slots__ = ("id", "inputs", "length", "prompt_ids", "max_new_tokens",
                 "future", "t_submit", "t_start", "t_first", "t_done",
                 "batch_size", "bucket", "slot", "joined_step",
                 "done_step", "replica", "t_handoff", "kv_blocks",
                 "trace", "tenant", "draft_tokens", "accepted_tokens",
                 "prefix_hit_tokens", "prefill_saved_ms")

    def __init__(self, inputs=None, length=None, prompt_ids=None,
                 max_new_tokens=None, tenant=None):
        self.id = next(_ids)
        self.inputs = inputs
        self.length = length
        self.prompt_ids = prompt_ids
        self.max_new_tokens = max_new_tokens
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.t_start = None
        self.t_first = None
        self.t_done = None
        self.batch_size = None
        self.bucket = None
        self.slot = None
        self.joined_step = None
        self.done_step = None
        # disaggregated-lane fields (paged path; see docs/observability.md)
        self.replica = None     # which dp replica served the request
        self.t_handoff = None   # decode lane adopted the prefilled KV
        self.kv_blocks = None   # blocks reserved for the request
        # observability (r12): the request-scoped span context (a
        # telemetry.tracing.Trace, None while tracing is off — every
        # serving call site guards on that None) and the SLO tenant
        self.trace = None
        self.tenant = tenant
        # speculative decoding + radix prefix cache (r19)
        self.draft_tokens = 0        # draft proposals scored for us
        self.accepted_tokens = 0     # proposals the target agreed with
        self.prefix_hit_tokens = None  # prompt tokens reused from cache
        self.prefill_saved_ms = None   # estimated prefill ms not spent

    def tpot_ms(self):
        """Time-per-output-token: decode milliseconds per generated
        token AFTER the first (TTFT owns the first) — None until done,
        and None for 1-token requests (no decode interval exists)."""
        if self.t_first is None or self.t_done is None or \
                not self.max_new_tokens or self.max_new_tokens < 2:
            return None
        return (self.t_done - self.t_first) * 1e3 \
            / (self.max_new_tokens - 1)

    def record(self, kind="serving.request", lane=None, status="ok",
               error=None):
        """The per-request JSONL record (emitted on completion, and —
        with ``status="error"`` — on the failure paths, so rejected or
        evicted requests still land in the stream with their replica
        and lane)."""
        rec = {
            "record": kind,
            "request_id": self.id,
            "status": status,
            "bucket": self.bucket,
            "batch_size": self.batch_size,
            "queue_wait_ms": (self.t_start - self.t_submit) * 1e3
            if self.t_start is not None else None,
            "total_ms": (self.t_done - self.t_submit) * 1e3
            if self.t_done is not None else None,
        }
        if lane is not None:
            rec["lane"] = lane
        if error is not None:
            rec["error"] = error
        if self.tenant is not None:
            rec["tenant"] = self.tenant
        if self.trace is not None:
            rec["trace_id"] = self.trace.trace_id
        if self.t_first is not None:
            rec["ttft_ms"] = (self.t_first - self.t_submit) * 1e3
        tpot = self.tpot_ms()
        if tpot is not None:
            rec["tpot_ms"] = tpot
        if self.slot is not None:
            rec["slot"] = self.slot
            rec["joined_step"] = self.joined_step
            rec["done_step"] = self.done_step
        if self.replica is not None:
            rec["replica"] = self.replica
        if self.kv_blocks is not None:
            rec["kv_blocks"] = self.kv_blocks
        if self.t_handoff is not None and self.t_first is not None:
            # prefill→decode KV handoff latency: first token emitted by
            # the prefill forward → decode lane adopted the slot
            rec["handoff_ms"] = (self.t_handoff - self.t_first) * 1e3
        if self.t_first is not None and self.t_start is not None:
            # prompt-processing wall time (dequeue → first token): the
            # figure the radix prefix cache exists to shrink
            rec["prefill_ms"] = (self.t_first - self.t_start) * 1e3
        if self.draft_tokens:
            rec["draft_tokens"] = self.draft_tokens
            rec["accepted_tokens"] = self.accepted_tokens
            rec["accept_rate"] = round(self.accepted_tokens
                                       / self.draft_tokens, 4)
        if self.prefix_hit_tokens is not None:
            rec["prefix_hit_tokens"] = self.prefix_hit_tokens
        if self.prefill_saved_ms is not None:
            rec["prefill_saved_ms"] = round(self.prefill_saved_ms, 3)
        return rec
