"""Power-of-two batch/length bucketing — the compiled-signature budget.

Every distinct (batch, length) shape that reaches a hybridized block is
one CachedOp signature: one trace + one XLA compile, priced once by
``telemetry/costs.py`` and cached forever.  Serving traffic with raw
shapes would compile per request-mix — the classic unpadded-dynamic-
batch churn the cachedop cache-miss counter exists to catch.  The
bucketing policy rounds both axes up to powers of two, so the whole
signature space is ``len(batch_buckets) × len(length_buckets)`` — small
and enumerable, every bucket compiled at most once, and the padding
waste bounded below 2× per axis.

Pure host-side shape math (numpy only, nothing traced) so the tier-1
bucketing tests are exact and the scheduler can call it per batch with
no device cost.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["pow2_bucket", "BucketPolicy", "pad_length", "pad_batch"]


def pow2_bucket(n, lo, hi):
    """Smallest power of two >= ``n``, clamped to [lo, hi].  ``n`` above
    ``hi`` raises — the caller's admission check rejects oversized
    requests before they reach a compile."""
    if n > hi:
        raise MXNetError(f"size {n} exceeds bucket ceiling {hi}")
    b = max(1, int(lo))
    while b < n:
        b *= 2
    return min(b, hi)


class BucketPolicy:
    """The signature budget: which (batch, length) shapes may compile.

    ``batch_bucket(n)`` / ``length_bucket(l)`` round up to the policy's
    power-of-two grid; ``signatures()`` enumerates the full compiled-
    shape space (its length is the hard ceiling on CachedOp signatures
    the server can create — the acceptance tests assert against it).
    """

    def __init__(self, max_batch=8, max_length=128, min_batch=1,
                 min_length=8):
        if max_batch < min_batch or max_length < min_length:
            raise MXNetError("bucket ceilings below floors")
        self.min_batch = int(min_batch)
        self.max_batch = int(max_batch)
        self.min_length = int(min_length)
        self.max_length = int(max_length)

    def batch_bucket(self, n):
        return pow2_bucket(n, self.min_batch, self.max_batch)

    def length_bucket(self, length):
        return pow2_bucket(length, self.min_length, self.max_length)

    def _axis(self, lo, hi):
        vals = []
        b = lo
        while b < hi:
            vals.append(b)
            b *= 2
        vals.append(hi)
        return vals

    def batch_buckets(self):
        return self._axis(self.min_batch, self.max_batch)

    def length_buckets(self):
        return self._axis(self.min_length, self.max_length)

    def signatures(self):
        """Every (batch_bucket, length_bucket) the policy can emit."""
        return [(b, l) for b in self.batch_buckets()
                for l in self.length_buckets()]


def pad_length(array, bucket, axis=0):
    """Zero-pad one example's ``axis`` up to ``bucket`` rows.  Padding
    is zeros: the serving bit-identity contract (docs/serving.md)
    requires models whose per-row outputs don't read other rows
    (position-wise heads), so pad rows change nothing in real rows and
    are sliced off at demux."""
    arr = np.asarray(array)
    n = arr.shape[axis]
    if n > bucket:
        raise MXNetError(f"length {n} exceeds bucket {bucket}")
    if n == bucket:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, bucket - n)
    return np.pad(arr, widths)


def pad_batch(examples, batch_bucket, length_bucket, axis=0):
    """Stack per-request examples into one (batch_bucket, ...) batch,
    length-padding each to ``length_bucket`` first.  Vacant batch rows
    repeat the first (padded) example — real values, so no denormal/NaN
    surprises — and are never demuxed back out."""
    if not examples:
        raise MXNetError("pad_batch needs at least one example")
    if len(examples) > batch_bucket:
        raise MXNetError(
            f"{len(examples)} examples exceed batch bucket {batch_bucket}")
    rows = [pad_length(e, length_bucket, axis=axis) for e in examples]
    while len(rows) < batch_bucket:
        rows.append(rows[0])
    return np.stack(rows, axis=0)
