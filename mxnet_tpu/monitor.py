"""Training monitor: per-layer output/parameter statistics.

Reference: ``python/mxnet/monitor.py:?`` — ``Monitor(interval, stat_func,
pattern, sort)`` installs an output callback on executors and prints
name→stat rows every ``interval`` batches (SURVEY §5).

TPU-native: works over Gluon blocks via the forward-hook mechanism
(``Block.register_forward_hook``) instead of the C++ executor's monitor
callback; the legacy ``Executor.set_monitor_callback`` path is also
supported via ``install_executor``.
"""
from __future__ import annotations

import re

from .base import MXNetError


def _default_stat(x):
    from . import ndarray as nd

    return nd.norm(x) / (x.size ** 0.5)


class Monitor:
    """Reference ``mx.monitor.Monitor``: ``tic()`` before forward,
    ``toc()`` after — returns ``[(step, name, stat_str), ...]`` for
    blocks/arrays whose name matches ``pattern``."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or _default_stat
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._handles = []

    # -- gluon path ----------------------------------------------------------
    def install(self, block, monitor_all=False):
        """Attach to every child block's forward output; with
        ``monitor_all`` also record inputs (reference
        ``monitor_all`` on executor attaches input arrays too).

        Hybridized blocks replay a compiled graph, so child forwards
        (and these hooks) only run at trace time.  The hooks therefore
        ride the numerics tier there: at trace time each monitored
        output is ``numerics.tap``-ed under a ``monitor.<name>`` path,
        baking the stat into the compiled graph as a side output that
        records on EVERY replay; ``toc()`` drains those entries.  The
        eager path (non-hybridized blocks) keeps the legacy stat_func
        queue unchanged."""
        if getattr(block, "_active", False):
            from .telemetry import numerics as _numerics

            # compiled-path recording needs the tier on, and any graph
            # traced before these hooks existed must re-trace with them
            if not _numerics.is_enabled():
                _numerics.enable()
            block._clear_cached_op()

        def make_hook(name):
            def hook(blk, inputs, outputs):
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else (outputs,)
                for i, o in enumerate(outs):
                    suffix = f"_output{i}" if len(outs) > 1 else "_output"
                    self._stat(name + suffix, o)
                if monitor_all:
                    for i, o in enumerate(inputs):
                        self._stat(f"{name}_input{i}", o)
            return hook

        for name, child in block._children.items():
            full = child.name or name
            self._handles.append(
                child.register_forward_hook(make_hook(full)))
            self.install(child, monitor_all)
        return self

    def uninstall(self):
        for h in self._handles:
            h.detach()
        self._handles = []

    # -- legacy executor path ------------------------------------------------
    def install_executor(self, executor):
        executor.set_monitor_callback(self._stat)

    def _stat(self, name, arr):
        if not self.re_pattern.match(name):
            return
        from .telemetry import numerics as _numerics

        if _numerics.is_enabled() \
                and _numerics._active_collector() is not None:
            # trace time under a hybridized graph: bake the stat into
            # the compile (the fixed numerics bundle, not stat_func —
            # arbitrary host callables cannot run inside a trace)
            _numerics.tap("monitor." + name, arr)
            return
        if not self.activated:
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        from .telemetry import numerics as _numerics

        # drain compiled-path stats every toc — a hybridized graph's
        # baked taps record on every replay, so off-interval entries
        # must be consumed (and dropped) to stay bounded
        compiled = _numerics.consume("monitor.") \
            if _numerics.is_enabled() else {}
        if not self.activated:
            return []
        self.activated = False
        stats = self._gather_stats([arr for _, _, arr in self.queue])
        res = [(step, name, s)
               for (step, name, _), s in zip(self.queue, stats)]
        last_step = self.step - 1
        for path, st in compiled.items():
            # display the l2 norm — the compiled path records the fixed
            # numerics bundle; stat_func applies on the eager path only
            res.append((last_step, path[len("monitor."):], str(st["l2"])))
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        return res

    @staticmethod
    def _gather_stats(arrs):
        """Stringify queued stats with ONE device→host transfer for all
        NDArray entries (stats stay on device until here — per-entry
        ``asnumpy`` would sync once per monitored layer)."""
        import numpy as np

        out = [None] * len(arrs)
        raws, slots = [], []
        for i, arr in enumerate(arrs):
            raw = getattr(arr, "_data", None)
            if hasattr(arr, "asnumpy") and raw is not None:
                raws.append(raw)
                slots.append(i)
            elif hasattr(arr, "asnumpy"):
                try:
                    out[i] = str(  # mxlint: allow=T1 (no raw buffer)
                        arr.asnumpy().ravel()[:1][0])
                except Exception as e:  # stat on in-graph array mid-trace
                    out[i] = f"<unreadable: {e}>"
            else:
                out[i] = str(arr)
        if raws:
            try:
                import jax

                from . import telemetry

                telemetry.count("host_sync")
                hosts = jax.device_get(raws)  # mxlint: allow=T1
            except Exception:
                hosts = None  # tracer in queue: fall back per entry
            for j, i in enumerate(slots):
                if hosts is not None:
                    try:
                        out[i] = str(np.asarray(hosts[j]).ravel()[:1][0])
                    except Exception as e:
                        out[i] = f"<unreadable: {e}>"
                else:
                    try:
                        out[i] = str(  # mxlint: allow=T1 (fallback)
                            arrs[i].asnumpy().ravel()[:1][0])
                    except Exception as e:
                        out[i] = f"<unreadable: {e}>"
        return out

    def toc_print(self):
        for step, name, stat in self.toc():
            print(f"Batch: {step:7d} {name:30s} {stat}")
