"""Autograd: imperative tape with ``record()/pause()`` semantics.

Reference: ``python/mxnet/autograd.py:?`` (user API) over
``src/imperative/imperative.cc:?`` (``Imperative::RecordOp`` builds a tape of
nnvm nodes; ``Imperative::Backward`` runs the nnvm ``Gradient`` pass over the
tape and executes the grad graph imperatively).

TPU-native redesign: there is no nnvm.  While recording, every invoked op is
evaluated through ``jax.vjp`` so the tape stores a ready-made backward closure
(residuals live on-device as jax arrays — the analog of the reference keeping
forward outputs alive via engine vars).  ``backward()`` walks the tape in
reverse-topological order, seeds head gradients, and accumulates cotangents
into ``.grad`` buffers of arrays marked with ``attach_grad()``.  A hybridized
block records ONE tape node for its whole cached graph (see
gluon/block.py), which is the analog of CachedOp's cached backward graph
(``src/imperative/cached_op.cc:?``) and is what makes the backward pass a
single fused XLA computation.

Semantics preserved from the reference:
  * ``record/pause`` nest arbitrarily; ``train_mode/predict_mode`` are
    orthogonal to recording.
  * ops on arrays not reachable from any ``attach_grad`` variable are not
    taped (reference prunes via the Gradient pass; we prune at record time).
  * multiple gradient paths sum; ``grad_req='add'`` accumulates across
    backward calls, ``'write'`` overwrites.
  * ``retain_graph=False`` frees the tape (residuals) after one backward.

  * ``create_graph=True`` (higher-order grad) IS supported — backward
    itself runs through the tape (``_backward_taped``), so grad-of-grad
    composes for every differentiable op; the reference only supports a
    per-op subset (tests ``tests/python/unittest/test_higher_order_grad
    .py:?``, here tests/test_autograd.py).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

from .base import MXNetError


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _AGState()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def _flush_on_record(prev, new):
    # Entering a recording region is a bulk-flush boundary: pending deferred
    # ops must land as real buffers before the tape starts observing inputs,
    # so tape semantics are identical to eager dispatch.
    if new and not prev:
        from . import engine as _engine

        if _engine._bulk_on:
            _engine.flush("record")


def set_recording(is_record: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, bool(is_record)
    _flush_on_record(prev, _STATE.recording)
    return prev


def set_training(train_mode: bool) -> bool:
    prev, _STATE.training = _STATE.training, bool(train_mode)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._rec, self._train = is_record, train_mode
        self._prev = None

    def __enter__(self):
        self._prev = (_STATE.recording, _STATE.training)
        if self._rec is not None:
            _STATE.recording = self._rec
            _flush_on_record(self._prev[0], self._rec)
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        _STATE.recording, _STATE.training = self._prev


def record(train_mode: bool = True):
    """``with autograd.record():`` — turn on recording (+training mode)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    """``with autograd.pause():`` — suspend recording (e.g. metric updates)."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class Node:
    """One taped op: a vjp closure plus graph wiring.

    ``inputs`` are the NDArray operands at call time (strong refs — the
    reference equivalently keeps AGInfo entries alive on the tape).
    ``out_avals`` records (shape, dtype) per output so backward can
    synthesise zero cotangents for unused outputs.
    """

    __slots__ = ("vjp", "inputs", "out_avals", "name", "single", "fun")

    def __init__(self, vjp, inputs, out_avals, name="", single=False,
                 fun=None):
        self.vjp = vjp
        self.inputs = inputs
        self.out_avals = out_avals
        self.name = name
        # True when the differentiated callable returned a bare array (jax.vjp
        # then expects a bare cotangent, not a 1-tuple)
        self.single = single
        # the pure forward function: kept so create_graph=True can rebuild
        # the vjp as a function of the primals (higher-order autograd)
        self.fun = fun

    def clear(self):
        self.vjp = None
        self.inputs = ()
        self.fun = None


def _zero_cotangent(shape, dtype):
    import jax
    import jax.numpy as jnp

    if np.issubdtype(np.dtype(dtype), np.floating) or np.dtype(dtype).name == "bfloat16":
        return jnp.zeros(shape, dtype)
    # non-differentiable outputs (int/bool) take float0 cotangents
    return np.zeros(shape, jax.dtypes.float0)


def _topo_order(head_nodes) -> List[Node]:
    """Iterative DFS postorder over the tape from the head nodes."""
    order, seen = [], set()
    stack = [(n, False) for n in head_nodes]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for inp in node.inputs:
            pnode = getattr(inp, "_node", None)
            if pnode is not None and id(pnode) not in seen:
                stack.append((pnode, False))
    return order  # postorder: producers before consumers


def _is_float0(x) -> bool:
    import jax

    return getattr(x, "dtype", None) == jax.dtypes.float0


def _np_astype(nd_arr, dt):
    """Taped dtype cast for cotangents (keeps the cast differentiable)."""
    from .ops.registry import apply_op

    return apply_op(lambda a: a.astype(dt), nd_arr, name="cot_cast")


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True):
    """Run backward from ``heads``; fill ``.grad`` of attached variables.

    Reference: ``MXAutogradBackwardEx`` → ``Imperative::Backward``
    (src/imperative/imperative.cc:?).
    """
    from .ndarray import NDArray  # late import to avoid cycle
    import jax.numpy as jnp

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    if len(heads) != len(head_grads):
        raise MXNetError("heads and head_grads length mismatch")

    # cotangent store: id(node) -> list per output slot
    cots = {}
    head_nodes = []
    # variables directly used as heads
    var_grads = {}  # id(NDArray) -> (ndarray, accumulated raw grad)

    def seed(arr, g):
        graw = g._data if isinstance(g, NDArray) else g
        if graw is None:
            graw = jnp.ones(arr.shape, arr.dtype)
        node = getattr(arr, "_node", None)
        if node is not None:
            slot_list = cots.setdefault(id(node), [None] * len(node.out_avals))
            idx = arr._oidx
            slot_list[idx] = graw if slot_list[idx] is None else slot_list[idx] + graw
            head_nodes.append(node)
        elif getattr(arr, "_req_grad", False):
            k = id(arr)
            if k in var_grads:
                var_grads[k] = (arr, var_grads[k][1] + graw)
            else:
                var_grads[k] = (arr, graw)
        else:
            raise MXNetError(
                "cannot differentiate a head that is not attached to the "
                "graph (call .attach_grad() or compute it inside "
                "autograd.record())")

    for h, hg in zip(heads, head_grads):
        seed(h, hg)

    order = _topo_order(head_nodes)
    for node in reversed(order):
        slot_list = cots.get(id(node))
        if slot_list is None:
            continue
        full = tuple(
            (s.astype(dt) if getattr(s, "dtype", None) is not None
             and not _is_float0(s) and np.dtype(s.dtype) != np.dtype(dt)
             else s) if s is not None else _zero_cotangent(shape, dt)
            for s, (shape, dt) in zip(slot_list, node.out_avals)
        )
        if node.vjp is None:
            raise MXNetError(
                "graph has already been freed; pass retain_graph=True to "
                "backward() to backprop twice through the same graph")
        in_cots = node.vjp(full[0] if node.single else full)
        for inp, g in zip(node.inputs, in_cots):
            if g is None or _is_float0(g):
                continue
            pnode = getattr(inp, "_node", None)
            if pnode is not None:
                pl = cots.setdefault(id(pnode), [None] * len(pnode.out_avals))
                i = inp._oidx
                pl[i] = g if pl[i] is None else pl[i] + g
            if getattr(inp, "_req_grad", False):
                k = id(inp)
                if k in var_grads:
                    var_grads[k] = (inp, var_grads[k][1] + g)
                else:
                    var_grads[k] = (inp, g)
        if not retain_graph:
            node.clear()

    # write into .grad buffers honouring grad_req
    for arr, g in var_grads.values():
        if arr._grad_req == "add":
            arr._grad._data = arr._grad._data + g
        elif arr._grad_req == "write":
            arr._grad._data = g.astype(arr.dtype) if g.dtype != arr._data.dtype else g
        # 'null': drop


def _backward_taped(heads, head_grads, retain_graph=True):
    """create_graph=True walk: the vjp of every node is re-derived from
    the stored pure function and applied THROUGH the op dispatcher, so the
    gradient computation itself lands on the tape (higher-order autograd —
    the reference supports this for a subset of ops, tests/python/unittest/
    test_higher_order_grad.py:?).  Returns {id(var): grad NDArray}."""
    import jax
    import jax.numpy as jnp

    from .ndarray import NDArray
    from .ops.registry import apply_op, wrap_raw

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    if len(heads) != len(head_grads):
        raise MXNetError("heads and head_grads length mismatch")

    cots = {}        # id(node) -> list of NDArray cotangents per slot
    head_nodes = []
    var_grads = {}   # id(var) -> (var, NDArray grad)

    def add_var(arr, g):
        k = id(arr)
        var_grads[k] = (arr, g if k not in var_grads
                        else var_grads[k][1] + g)

    for h, hg in zip(heads, head_grads):
        g = hg if hg is not None else wrap_raw(jnp.ones(h.shape, h.dtype))
        node = getattr(h, "_node", None)
        if node is not None:
            sl = cots.setdefault(id(node), [None] * len(node.out_avals))
            i = h._oidx
            sl[i] = g if sl[i] is None else sl[i] + g
            head_nodes.append(node)
        elif getattr(h, "_req_grad", False):
            add_var(h, g)
        else:
            raise MXNetError("head not attached to the graph")

    order = _topo_order(head_nodes)
    with record():
        for node in reversed(order):
            sl = cots.get(id(node))
            if sl is None:
                continue
            if node.fun is None:
                raise MXNetError(
                    f"op {node.name!r} cannot participate in "
                    "create_graph=True backward (no stored forward fn; "
                    "the reference likewise supports higher-order grad "
                    "for a subset of ops only)")
            full = []
            for s, (sh, dt) in zip(sl, node.out_avals):
                if s is None:
                    s = wrap_raw(_zero_cotangent(sh, dt))
                elif np.dtype(s.dtype) != np.dtype(dt) and \
                        not _is_float0(s._data):
                    s = _np_astype(s, dt)  # same coercion as backward()
                full.append(s)
            n_in = len(node.inputs)
            single = node.single
            fun = node.fun

            def back_fun(*raws, _fun=fun, _n=n_in, _single=single):
                primals, cts = raws[:_n], raws[_n:]
                _out, vjp = jax.vjp(_fun, *primals)
                gs = vjp(cts[0] if _single else tuple(cts))
                # float0 (int primals) → zeros so results stay arrays
                return tuple(
                    jnp.zeros(p.shape, p.dtype) if _is_float0(g) else g
                    for g, p in zip(gs, primals))

            outs = apply_op(back_fun, *node.inputs, *full,
                            name=f"bwd_{node.name}")
            outs = (outs,) if isinstance(outs, NDArray) else outs
            for inp, g in zip(node.inputs, outs):
                pnode = getattr(inp, "_node", None)
                if pnode is not None:
                    pl = cots.setdefault(id(pnode),
                                         [None] * len(pnode.out_avals))
                    i = inp._oidx
                    pl[i] = g if pl[i] is None else pl[i] + g
                if getattr(inp, "_req_grad", False):
                    add_var(inp, g)
            if not retain_graph:
                node.clear()
    return {k: g for k, (_v, g) in var_grads.items()}, var_grads


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph: bool = False, train_mode: bool = True):
    """Functional gradient: return grads of ``heads`` w.r.t. ``variables``
    without touching ``.grad`` buffers (reference: ``autograd.grad``,
    python/mxnet/autograd.py:?).  With ``create_graph=True`` the returned
    grads are attached to the tape, so a second ``backward()`` through
    them yields higher-order gradients."""
    from .ndarray import NDArray
    import jax.numpy as jnp

    if isinstance(variables, NDArray):
        variables = [variables]
    if retain_graph is None:
        retain_graph = create_graph

    if create_graph:
        saved = [(getattr(v, "_req_grad", False)) for v in variables]
        for v in variables:
            v._req_grad = True
        try:
            gmap, _ = _backward_taped(heads, head_grads,
                                      retain_graph=True)
        finally:
            for v, rq in zip(variables, saved):
                v._req_grad = rq
        out = []
        for v in variables:
            g = gmap.get(id(v))
            if g is None:
                g = NDArray(jnp.zeros(v.shape, v.dtype))
            out.append(g)
        return out

    # Temporarily mark variables, run backward into scratch buffers.
    saved = []
    for v in variables:
        saved.append((getattr(v, "_req_grad", False), getattr(v, "_grad", None),
                      getattr(v, "_grad_req", "null")))
        v._req_grad = True
        v._grad_req = "write"
        v._grad = NDArray(jnp.zeros(v.shape, v.dtype))
    try:
        backward(heads, head_grads, retain_graph=retain_graph,
                 train_mode=train_mode)
        out = [v._grad for v in variables]
    finally:
        for v, (rq, g, req) in zip(variables, saved):
            v._req_grad, v._grad, v._grad_req = rq, g, req
    return out


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference: ``autograd.mark_variables`` — associate grad buffers."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._req_grad = req != "null"
        v._grad = g
        v._grad_req = req


class Function:
    """Custom differentiable function (reference ``autograd.Function``,
    python/mxnet/autograd.py:? — the python analog of CustomOp).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` with NDArray math.  Gradients computed
    in ``backward`` are raw (not taped) in this round.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def __call__(self, *inputs):
        from .ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = (outputs,) if single else tuple(outputs)
        if is_recording():
            fn = self

            def vjp(cotangents):
                from .ndarray import NDArray as ND

                with pause():
                    gs = fn.backward(*[ND(c) for c in cotangents])
                if isinstance(gs, ND):
                    gs = (gs,)
                return tuple(g._data if g is not None else None for g in gs)

            node = Node(vjp, list(inputs),
                        [(o.shape, o.dtype) for o in outs],
                        name=type(self).__name__)
            for i, o in enumerate(outs):
                o._node = node
                o._oidx = i
        return outputs if single else outs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError


def get_symbol(x):  # pragma: no cover - compat stub
    raise NotImplementedError(
        "autograd.get_symbol (legacy symbolic extraction) is not supported; "
        "use HybridBlock.export for graph capture")
