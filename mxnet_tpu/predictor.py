"""Standalone inference predictor — the C predict API, re-scoped to Python.

Reference: ``src/c_api/c_predict_api.cc:?`` + ``include/mxnet/
c_predict_api.h:?`` (SURVEY §3.5): ``MXPredCreate(symbol_json, param_bytes,
dev, input_shapes)`` → ``MXPredSetInput`` → ``MXPredForward`` →
``MXPredGetOutput``; the serving surface language bindings and deployment
stacks build on.

TPU-native redesign: the predictor binds either serving format —
- a gluon ``export_block`` artifact (symbol-json meta + StableHLO program +
  params): loaded as a sealed XLA executable, the north star's serving
  path;
- a legacy nnvm symbol-json + ``.params`` checkpoint (module
  ``save_checkpoint`` output, including files written by the reference):
  replayed through the op registry and compiled per input shape.

Both compile once per input signature (the MXPredCreate bind-once
contract) and run label-free.
"""
from __future__ import annotations

import json
import os
import tempfile

import numpy as np

from .base import MXNetError
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["Predictor", "create"]

#: reviewed signature budget (mxlint T15): forward compiles one CachedOp
#: graph per input-shape bucket; with a BucketPolicy attached the ceiling
#: is len(policy.signatures()), and the serving bench gates on it
__compile_signatures__ = {
    "predictor": "len(BucketPolicy.signatures()) per model",
}


class Predictor:
    """Bound inference session (reference ``MXPredCreate``).

    Parameters
    ----------
    symbol : str | dict
        Path to a ``*-symbol.json`` file, a JSON string, or the parsed
        dict.
    params : str | bytes
        Path to a ``.params`` file or its raw bytes.
    input_names : list of str, optional
        Graph input names.  Defaults to ``input_shapes`` keys, the
        export-time metadata (StableHLO artifacts), or the symbol args not
        present in ``params`` (nnvm graphs) — the c_predict_api contract
        where ``input_keys`` is explicit is the first of these.
    input_shapes : dict, optional
        name → shape; used only to infer ``input_names`` and to validate
        the first ``forward``.
    stablehlo : str | bytes, optional
        For StableHLO artifacts when ``symbol`` is passed as dict/JSON
        (no directory to resolve the relative ``stablehlo_file`` against):
        the artifact path or its raw bytes.
    """

    def __init__(self, symbol, params, ctx=None, input_names=None,
                 input_shapes=None, stablehlo=None):
        from .gluon.symbol_block import import_block, load_symbol_json

        self._tmpdir = None
        symbol_file = self._materialize_symbol(symbol)
        param_file = self._materialize_params(params)
        meta = load_symbol_json(symbol_file)
        if "stablehlo_file" in meta:
            symbol_file = self._resolve_stablehlo(symbol_file, meta,
                                                  stablehlo)
            meta = load_symbol_json(symbol_file)
        self._input_shapes = dict(input_shapes or {})
        if input_names is None:
            if self._input_shapes:
                input_names = list(self._input_shapes)
            elif "input_names" in meta:
                input_names = list(meta["input_names"])
            elif "nodes" in meta:
                input_names = self._infer_inputs_from_graph(meta, param_file)
            elif "input_shapes" in meta:
                # stablehlo export: positional inputs; synthesize the
                # reference's default data names
                n = len(meta["input_shapes"])
                input_names = ["data"] if n == 1 else \
                    [f"data{i}" for i in range(n)]
            else:
                raise MXNetError(
                    "cannot infer input names; pass input_names or "
                    "input_shapes")
        elif isinstance(input_names, str):
            input_names = [input_names]
        self._input_names = list(input_names)
        self._block = import_block(symbol_file, self._input_names,
                                   param_file, ctx=ctx)
        hybridize = getattr(self._block, "hybridize", None)
        if hybridize is not None and hasattr(self._block, "hybrid_forward"):
            try:
                hybridize(static_alloc=True)
            except MXNetError:
                pass
        self._inputs = {}
        self._outputs = None
        self._seen_signatures = 0

    # -- input materialisation ------------------------------------------------
    def _tmp(self):
        if self._tmpdir is None:
            import shutil
            import weakref

            self._tmpdir = tempfile.mkdtemp(prefix="mxt_pred_")
            # params copies can be GB-scale; reclaim on GC
            weakref.finalize(self, shutil.rmtree, self._tmpdir,
                             ignore_errors=True)
        return self._tmpdir

    def _resolve_stablehlo(self, symbol_file, meta, stablehlo):
        """Make ``stablehlo_file`` resolvable from the symbol file's dir —
        materializing bytes or rewriting to an absolute path.  Returns the
        symbol file to bind (a tmpdir copy when a rewrite is needed; the
        caller's file is never modified)."""
        ref = meta["stablehlo_file"]
        if isinstance(stablehlo, (bytes, bytearray)):
            path = os.path.join(self._tmp(), "model.stablehlo")
            with open(path, "wb") as f:
                f.write(stablehlo)
        elif stablehlo is not None:
            path = os.path.abspath(stablehlo)
        else:
            candidate = os.path.join(
                os.path.dirname(os.path.abspath(symbol_file)), ref)
            if os.path.exists(candidate):
                return symbol_file  # file-based layout resolves as-is
            raise MXNetError(
                f"stablehlo artifact {ref!r} not found next to the symbol "
                "meta; pass stablehlo=<path or bytes> when creating the "
                "Predictor from a symbol dict/JSON string")
        patched = os.path.join(self._tmp(), "model-symbol.json")
        with open(patched, "w") as f:
            json.dump(dict(meta, stablehlo_file=path), f)
        return patched

    def _materialize_symbol(self, symbol):
        if isinstance(symbol, dict):
            path = os.path.join(self._tmp(), "model-symbol.json")
            with open(path, "w") as f:
                json.dump(symbol, f)
            return path
        if isinstance(symbol, str) and not os.path.exists(symbol):
            # JSON text (reference MXPredCreate takes the json STRING)
            try:
                json.loads(symbol)
            except json.JSONDecodeError:
                raise MXNetError(
                    f"symbol is neither an existing file nor JSON: "
                    f"{symbol[:80]!r}")
            path = os.path.join(self._tmp(), "model-symbol.json")
            with open(path, "w") as f:
                f.write(symbol)
            return path
        return symbol

    def _materialize_params(self, params):
        if isinstance(params, (bytes, bytearray)):
            path = os.path.join(self._tmp(), "model.params")
            with open(path, "wb") as f:
                f.write(params)
            return path
        return params

    @staticmethod
    def _infer_inputs_from_graph(meta, param_file):
        from . import serialization

        saved = set()
        if param_file is not None:
            saved = {k.split(":", 1)[-1]
                     for k in serialization.load_ndarrays(param_file)}
        nodes = meta["nodes"]
        names = [nodes[i]["name"] for i in meta["arg_nodes"]
                 if nodes[i]["name"] not in saved]
        if not names:
            raise MXNetError("no unbound args found to use as inputs")
        return names

    # -- the MXPred* surface --------------------------------------------------
    @property
    def input_names(self):
        return list(self._input_names)

    def set_input(self, name, array):
        """``MXPredSetInput``: stage one named input."""
        if name not in self._input_names:
            raise MXNetError(
                f"unknown input {name!r}; expected one of "
                f"{self._input_names}")
        if not isinstance(array, NDArray):
            array = nd.array(np.asarray(array))
        want = self._input_shapes.get(name)
        if want is not None and tuple(array.shape) != tuple(want):
            raise MXNetError(
                f"input {name!r} has shape {tuple(array.shape)}, "
                f"bound to {tuple(want)}; use reshape()")
        self._inputs[name] = array

    def reshape(self, new_input_shapes):
        """``MXPredReshape``: rebind to new input shapes (XLA recompiles
        per signature on the next forward; previous signatures stay
        cached)."""
        self._input_shapes.update(new_input_shapes)
        self._inputs.clear()
        self._outputs = None

    def forward(self, **inputs):
        """``MXPredForward``: run the bound graph on the staged (or
        keyword-passed) inputs."""
        from . import autograd
        from . import telemetry

        for k, v in inputs.items():
            self.set_input(k, v)
        missing = [n for n in self._input_names if n not in self._inputs]
        if missing:
            raise MXNetError(f"inputs not set: {missing}")
        args = [self._inputs[n] for n in self._input_names]
        with autograd.pause(), telemetry.span("predictor.forward"):
            out = self._block(*args)
        self._outputs = list(out) if isinstance(out, (list, tuple)) \
            else [out]
        self._note_signature(args)
        return self._outputs

    # -- compile-cache observability ------------------------------------------
    def cache_stats(self):
        """Per-signature compile-cache counters for the bound graph:
        ``{"hits", "misses", "signatures"}``.  One miss = one
        trace+compile of a new (input shapes/dtypes, mode, platform)
        signature; a serving layer's bucketing policy is verified by
        asserting ``signatures`` stays bounded under mixed traffic.
        All-zero when the block runs un-hybridized (imperative
        fallback)."""
        cop = getattr(self._block, "_cached_op", None)
        if cop is None:
            return {"hits": 0, "misses": 0, "signatures": 0}
        return cop.cache_stats()

    def _note_signature(self, args):
        """Post-forward bookkeeping: count ``predictor.compile`` /
        ``predictor.cache_hit`` telemetry from the CachedOp cache delta,
        and register a new signature's compiled graph in the cost
        registry under kind ``"predictor"`` (registration only — the
        CachedOp site already attributes per-execution flops)."""
        from . import telemetry
        from .telemetry import costs as _costs

        cop = getattr(self._block, "_cached_op", None)
        if cop is None:
            return
        n = len(cop._graphs)
        if n <= self._seen_signatures:
            telemetry.count("predictor.cache_hit")
            return
        self._seen_signatures = n
        telemetry.count("predictor.compile")
        from .telemetry import retrace as _retrace

        if _retrace._enabled and cop._graphs:
            # registered compile site: the newest CachedOp cache key is
            # the signature this forward just compiled
            _retrace.observe(
                "predictor", id(self),
                _retrace.cachedop_components(next(reversed(cop._graphs))),
                site="mxnet_tpu.predictor:Predictor.forward")
        if _costs._enabled and cop._graphs:
            # dict is insertion-ordered: the newest graph is the one this
            # forward just compiled
            g = next(reversed(cop._graphs.values()))
            try:
                import jax

                p_raws = [p.data()._data for p in g.params]
                in_raws = [a._data for a in args]
                _costs.note("predictor", (id(self), n), g._fwd,
                            (p_raws, in_raws, jax.random.PRNGKey(0)),
                            attribute=False,
                            site="mxnet_tpu.predictor:Predictor.forward")
            except Exception:
                pass  # registry entries are best-effort observability

    def get_output(self, index=0):
        """``MXPredGetOutput``."""
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        if not 0 <= index < len(self._outputs):
            raise MXNetError(
                f"output index {index} out of range "
                f"({len(self._outputs)} outputs)")
        return self._outputs[index]

    @property
    def num_outputs(self):
        if self._outputs is None:
            raise MXNetError("call forward() before num_outputs")
        return len(self._outputs)

    def predict(self, data):
        """Convenience: single-input forward → first output."""
        self.forward(**{self._input_names[0]: data})
        return self.get_output(0)


def create(symbol, params, ctx=None, input_names=None, input_shapes=None,
           stablehlo=None):
    """``MXPredCreate`` analog."""
    return Predictor(symbol, params, ctx=ctx, input_names=input_names,
                     input_shapes=input_shapes, stablehlo=stablehlo)
