"""Host-offloaded optimizer state: momentum / f32 masters in host RAM.

``Trainer(..., offload="host")`` keeps the per-parameter optimizer
state and multi-precision masters OFF the accelerator between steps:

- at init (and after every commit) state buffers live in host memory —
  on TPU via the ``pinned_host`` memory kind of the array's own
  sharding (layout-preserving, so H2D is a straight DMA); on backends
  without memory kinds (CPU CI) the movement degenerates to a
  same-device copy but the contract — fetch, donate the fetched copy,
  stash the result back — is exercised identically;
- at step time the trainer FETCHES device copies (async ``device_put``,
  overlappable with grad allreduce), feeds those to the donating fused
  update exactly as device-resident state would be fed (donation
  contract and sanitizer unchanged — the donated buffers are the
  transient device copies), and STASHES the fresh state back to host
  without blocking the step.

The module keeps byte counters (`offload_bytes` in the per-step JSONL
rides :func:`resident_bytes`) and per-step H2D/D2H traffic lands in the
telemetry counters ``offload.h2d_bytes`` / ``offload.d2h_bytes``.
"""

_resident_bytes = 0     # bytes currently parked in host memory
_h2d_total = 0
_d2h_total = 0


def resident_bytes():
    """Optimizer-state bytes currently host-resident (0 when no
    offloading trainer is live)."""
    return _resident_bytes


def stats():
    return {"resident_bytes": _resident_bytes,
            "h2d_bytes_total": _h2d_total, "d2h_bytes_total": _d2h_total}


def _nbytes(raw):
    import numpy as np

    return int(np.prod(raw.shape)) * np.dtype(raw.dtype).itemsize


def _host_sharding(raw):
    """The array's own sharding re-homed to host memory, or None when
    the backend has no addressable host memory kind (CPU CI)."""
    try:
        sh = raw.sharding.with_memory_kind("pinned_host")
        # probe: device_put below raises on backends that advertise the
        # kind but cannot transfer to it
        return sh
    except Exception:
        return None


def _count(name, n):
    try:
        from .. import telemetry

        telemetry.count(name, n)
    except Exception:
        pass


def stash(arr):
    """Move an NDArray's buffer to host memory in place (D2H, async).
    Returns the NDArray; a backend without host memory kinds keeps the
    buffer where it is (copy elided) but still books it as
    host-resident so the accounting is backend-independent."""
    global _resident_bytes, _d2h_total
    import jax

    raw = arr._data
    host = _host_sharding(raw)
    if host is not None:
        try:
            raw = jax.device_put(raw, host)
        except Exception:
            pass
    arr._data = raw
    n = _nbytes(raw)
    _resident_bytes += n
    _d2h_total += n
    _count("offload.d2h_bytes", n)
    return arr


def fetch(arr):
    """Device copy of a host-stashed NDArray's buffer (H2D, async).
    Returns the RAW device array — the caller feeds it to a donating
    jitted call; the NDArray keeps its host buffer until the fresh
    result is stashed over it."""
    global _h2d_total
    import jax

    raw = arr._data
    n = _nbytes(raw)
    _h2d_total += n
    _count("offload.h2d_bytes", n)
    try:
        sharding = raw.sharding
        kind = getattr(sharding, "memory_kind", None)
        if kind and kind != "device":
            return jax.device_put(raw, sharding.with_memory_kind("device"))
    except Exception:
        pass
    # no memory kinds (CPU CI): an explicit copy keeps the donation
    # contract honest — the donated buffer is the transient copy, never
    # the host-resident original
    return jax.device_put(raw, raw.sharding)


def release(arr):
    """Book an offloaded NDArray's buffer as no longer host-resident
    (called when a fresh result replaces it)."""
    global _resident_bytes
    _resident_bytes = max(0, _resident_bytes - _nbytes(arr._data))


def reset():
    """Drop all counters (tests)."""
    global _resident_bytes, _h2d_total, _d2h_total
    _resident_bytes = _h2d_total = _d2h_total = 0
