"""Offline AOT-lowering engine behind the HBM planner's cold path.

Extracted from ``tools/scale_proof.py`` (which now consumes this module
— the same library-extraction move PR 9 made for partition rules): the
shell-parameter trick, the scan-over-stacked-layers remat forward, the
XLA memory-analysis harvest, the XLA:CPU bf16-upcast correction, and
the fit-verdict construction.  ``tools/scale_proof.py`` remains the CLI
that turns these into committed ``*_LOWER_*.json`` artifacts; the
planner (:mod:`mxnet_tpu.memory.planner`) calls the same functions when
a cold signature needs a real lowering, and reads the committed
artifacts back when offline TPU lowering is unavailable (libtpu holds a
process-wide lockfile and is absent on CI).

Nothing here materializes a parameter array: parameters enter the
jitted step as ``jax.ShapeDtypeStruct`` avals sharded by the SAME
partition engine the real placement path uses.
"""
import glob
import os
import re

#: v5e usable-HBM budget the topology compiler enforces (observed:
#: "Used 15.78G of 15.75G hbm" RESOURCE_EXHAUSTED on overflow).
TPU_BUDGET_GIB = 15.75

#: reviewed signature budget (mxlint T15): checkpoint_wrap adds no
#: signatures of its own — the remat-wrapped callable compiles under the
#: wrapped site's budget, one program per (layer avals, remat policy)
__compile_signatures__ = {
    "remat_forward": "1 per (wrapped layer avals, remat policy); "
                     "tracks the wrapped site's budget",
}

LAYER0_PREFIX = "model.layers.0."


def shell_params(net):
    """Replace every Parameter's storage with an empty shell handle:
    tracing swaps tracers into ``._data`` so no real array is needed
    (the CachedOp handle-swap trick, gluon/block.py _CachedGraph).
    Returns ``(params, shapes, shells, n_params)``."""
    import numpy as np

    from ..ndarray import NDArray

    params = net._collect_params_with_prefix()
    shapes, shells = {}, {}
    for name, p in params.items():
        shape = tuple(int(s) for s in (p.shape or ()))
        assert shape and all(s > 0 for s in shape), \
            f"{name} shape not fully declared: {p.shape}"
        shapes[name] = shape
        a = NDArray.__new__(NDArray)
        a._data = None
        a._node = None
        a._oidx = 0
        a._req_grad = False
        a._grad = None
        a._grad_req = "null"
        p._data = a
        shells[name] = a
    n_params = sum(int(np.prod(s)) for s in shapes.values())
    return params, shapes, shells, n_params


def remat_forward(net, shells, p_raws, ids_r, head=True,
                  remat="layer", act_sharding=None):
    """embed -> lax.scan(checkpoint_wrap(layer)) -> norm -> head.

    Same math as ``LlamaModel.hybrid_forward`` + ``_lm_head``, shaped
    the way a production TPU trainer compiles it (r4 memory findings):

    - **scan over stacked layer params** (p_raws carries ONE (L, ...)
      array per layer parameter; the layer-0 Block is the template,
      handle-swapped per iteration — the pipeline machinery's trick).
      A python layer loop gave XLA one copy of every per-layer buffer
      (collective buffers included): ~1 GiB x L of temp that scan
      eliminates by construction, and L x faster tracing.
    - **the remat tier wraps the scan body** (``policy.checkpoint_wrap``
      — "layer" keeps only the (L, B, T, H) layer-boundary stack for
      the backward; "dots" saves matmul outputs; "none" saves all).
    - **one-hot MATMUL embedding lookup**: the transpose of a gather
      over the vocab-sharded table is a scatter-add that GSPMD lowers
      by materializing the FULL f32 table per device (measured 2
      GiB/device on 8B); as a matmul, lookup AND gradient are ordinary
      sharded contractions.
    - ``act_sharding`` pins the residual stream (P('dp', None, None))
      at the scan boundary so GSPMD can't replicate it over dp.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ndarray import NDArray
    from .policy import checkpoint_wrap

    def pin(x):
        if act_sharding is not None:
            return jax.lax.with_sharding_constraint(x, act_sharding)
        return x

    for name, sh in shells.items():
        if not name.startswith("model.layers."):
            sh._data = p_raws[name]
    table = p_raws["model.embed_tokens.weight"]
    onehot = jax.nn.one_hot(ids_r, table.shape[0], dtype=table.dtype)
    h = pin(jnp.einsum("btv,vh->bth", onehot, table))

    template = net.model.layers[0]
    suffixes = [n[len(LAYER0_PREFIX):] for n in shells
                if n.startswith(LAYER0_PREFIX)]

    def apply_layer(pslice, hr):
        for sfx in suffixes:
            shells[LAYER0_PREFIX + sfx]._data = pslice[sfx]
        return pin(template(NDArray(hr))._data)

    wrapped = checkpoint_wrap(apply_layer, remat)

    def body(hr, pslice):
        return wrapped(pslice, hr), ()

    stacked = {sfx: p_raws["stacked_layers." + sfx] for sfx in suffixes}
    h, _ = lax.scan(body, h, stacked)

    h = net.model.norm(NDArray(h))._data
    if not head:
        return h
    if net._cfg.tie_embeddings:
        return h @ p_raws["model.embed_tokens.weight"].T
    return net.lm_head(NDArray(h))._data


def cpu_upcast_artifact_bytes(n_layers, dump_dir):
    """Sum the preallocated-temp slots that are f32 CONVERTS of bf16
    layer-stacked arrays (shape leading dim == n_layers, producer a
    convert fusion) in the dumped buffer assignment — the XLA:CPU
    bf16-dot upcast artifact quantified in the fit verdict.  Returns
    (bytes, [slot descriptions])."""
    files = glob.glob(os.path.join(dump_dir, "*buffer-assignment.txt"))
    if not files:
        return 0, []
    txt = open(max(files, key=os.path.getmtime)).read()
    m = re.search(r"allocation \d+: size \d+, preallocated-temp:(.*?)"
                  r"(?=\nallocation |\Z)", txt, re.S)
    if not m:
        return 0, []
    slots = {}
    for name, sz, off, shape in re.findall(
            r"value: <\d+ ([\w.\-]+) @0> \(size=(\d+),offset=(\d+)\): "
            r"(\S+)", m.group(1)):
        slots.setdefault((int(off), int(sz)), []).append((name, shape))
    total, picked = 0, []
    for (off, sz), vals in slots.items():
        for name, shape in vals:
            if re.match(rf"f32\[{n_layers},", shape) and "convert" in name:
                total += sz
                picked.append(f"{shape} {name} ({sz / 2**20:.0f} MB)")
                break
    return total, picked


def harvest_memory(compiled):
    """XLA ``memory_analysis()`` of a compiled executable as a plain
    dict of the five per-device ``*_size_in_bytes`` figures (the keys
    every committed ``xla_memory_analysis_per_device`` block carries)."""
    mem = {}
    try:
        ma = compiled.memory_analysis()
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "alias_size_in_bytes", "temp_size_in_bytes",
                  "generated_code_size_in_bytes"):
            v = getattr(ma, k, None)
            if v is not None:
                mem[k] = int(v)
    except Exception as e:
        mem["unavailable"] = str(e)
    return mem


def fit_verdict(mem, backend, cpu_artifact_b=0, cpu_artifact_slots=()):
    """The fit-verdict block of a lowering artifact, byte-identical in
    shape to what scale_proof has committed since r4.

    TPU backend: the STRONGEST signal is that the compile SUCCEEDED at
    all — the topology compiler enforces the device's usable HBM budget
    (15.75 GiB on v5e) and fails RESOURCE_EXHAUSTED when the scheduled
    program exceeds it; args+temp is a supplementary upper bound.

    CPU backend: args+temp resident, minus the XLA:CPU bf16-upcast
    artifact (f32 LICM-hoisted converts of bf16 stacks a TPU lowering
    never materializes), against a raw 16 GiB budget.
    """
    if "argument_size_in_bytes" not in mem:
        return {}
    args_b = mem["argument_size_in_bytes"]
    temp_b = mem.get("temp_size_in_bytes", 0)
    resident = args_b + temp_b
    if backend == "tpu":
        return {
            "fits_hbm_compiler_enforced": True,
            "compiler_enforced_budget_gib": TPU_BUDGET_GIB,
            "resident_bytes_per_device_args_plus_temp": resident,
            "resident_gib_per_device_upper_bound": round(
                resident / 2 ** 30, 2),
            "upper_bound_note": "args+temp, ignores donation aliasing "
                                "— the compiler's own scheduler fit is "
                                "the load-bearing verdict",
        }
    corrected = resident - cpu_artifact_b
    return {
        "resident_bytes_per_device_args_plus_temp": resident,
        "resident_gib_per_device": round(resident / 2 ** 30, 2),
        "cpu_bf16_upcast_artifact_bytes": cpu_artifact_b,
        "cpu_bf16_upcast_artifact_gib": round(
            cpu_artifact_b / 2 ** 30, 2),
        "cpu_bf16_upcast_artifact_slots": list(cpu_artifact_slots),
        "resident_gib_corrected_for_cpu_artifact": round(
            corrected / 2 ** 30, 2),
        "hbm_budget_gib": 16.0,
        "fits_16gib_raw_cpu_analysis": bool(resident < 16 * 2 ** 30),
        "fits_16gib_corrected": bool(corrected < 16 * 2 ** 30),
    }
