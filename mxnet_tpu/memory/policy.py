"""Auto-remat policy: a small tier ladder picked by the planner.

Three tiers trade recompute FLOPs for activation memory:

- ``"none"``   — save every activation; zero recompute (cheapest step).
- ``"dots"``   — ``jax.checkpoint_policies.dots_saveable``: matmul
  outputs survive to the backward, elementwise chains recompute.
- ``"layer"``  — full ``jax.checkpoint`` at the natural block boundary
  (the per-decoder-layer scan body for llama; the whole traced graph
  for a generic hybridized block): only boundary activations survive.

``"auto"`` asks the planner for the cheapest tier that fits the device
budget with a configurable margin — models with headroom stop paying
blanket recompute.  Every remat decision in the tree flows through
:func:`checkpoint_wrap`; hand-rolled ``jax.checkpoint`` in model code
is an mxlint T9 violation.
"""

TIERS = ("none", "dots", "layer")

#: historical/bool spellings accepted at every remat surface
_ALIASES = {
    None: "none", False: "none", True: "layer",
    "full": "layer", "per_layer": "layer", "per-layer": "layer",
    "dots_saveable": "dots",
}

#: default headroom the auto policy insists on below the device budget
DEFAULT_MARGIN = 0.10

#: last auto-policy decision, for telemetry's ``remat_policy`` field
#: and the OOM prescription: {"tier", "mode", "predicted_peak_bytes"}
_last_policy = None


def normalize(tier):
    """Canonical tier name for any accepted spelling ("auto" passes
    through); raises on garbage rather than silently not remat-ing."""
    t = _ALIASES.get(tier, tier)
    if t == "auto" or t in TIERS:
        return t
    raise ValueError(
        f"unknown remat tier {tier!r}: expected one of {TIERS + ('auto',)}")


def checkpoint_wrap(fn, tier):
    """Wrap ``fn`` per the (normalized) tier — the ONE sanctioned
    ``jax.checkpoint`` site for model code."""
    t = normalize(tier)
    if t == "auto":
        raise ValueError("resolve 'auto' via select_tier()/auto_tier() "
                         "before wrapping")
    if t == "none":
        return fn
    import jax

    if t == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_saveable)
    return jax.checkpoint(fn)


def record_policy(tier, mode, plan=None):
    """Note the decision (telemetry reads it back via
    ``memory.telemetry_fields``)."""
    global _last_policy
    _last_policy = {
        "tier": tier, "mode": mode,
        "predicted_peak_bytes": (
            int(plan.predicted_peak_bytes) if plan is not None else None),
    }
    return _last_policy


def last_policy():
    return _last_policy


def reset():
    """Forget the last decision (benchmark/test lane isolation)."""
    global _last_policy
    _last_policy = None


def select_tier(plan_for_tier, margin=None, record=True):
    """Cheapest tier whose plan fits with ``margin`` headroom below the
    budget; escalates up the ladder, settling on "layer" (the most
    memory-frugal tier) even when nothing fits — the plan's ``fits``
    flag carries the bad news.  ``plan_for_tier(tier) -> Plan``.
    Returns ``(tier, plan)``."""
    margin = DEFAULT_MARGIN if margin is None else margin
    tier, plan = None, None
    for tier in TIERS:
        plan = plan_for_tier(tier)
        if plan.predicted_peak_bytes <= plan.budget_bytes * (1 - margin):
            break
    if record:
        record_policy(tier, "auto", plan)
    return tier, plan


def auto_tier(params, mesh=None, rules=None, optimizer=None,
              batch_bytes=0, activation_hint=None, budget=None,
              margin=None, record=True):
    """Resolve "auto" for a concrete model: plan each tier with the
    analytic planner and return ``(tier, plan)`` via
    :func:`select_tier`.  ``params`` as accepted by
    :func:`planner.plan_model`; ``activation_hint`` (bytes at tier
    "none") scales down the ladder when the caller measured it."""
    from . import planner

    def plan_for(tier):
        return planner.plan_model(
            params, mesh=mesh, rules=rules, optimizer=optimizer,
            batch_bytes=batch_bytes, remat=tier,
            activation_hint=activation_hint, budget=budget)

    return select_tier(plan_for, margin=margin, record=record)
