"""Pre-dispatch HBM planner: predict per-device peak BEFORE compiling.

The planner answers "will this (model, batch, mesh, remat tier, offload
config) fit the device budget?" from three sources, cheapest first:

1. **analytic** — exact per-device byte math over the declared
   parameter shapes, sharded by the SAME partition-rule engine the real
   placement path uses (``parallel.partition``), plus optimizer-state /
   master-copy multipliers and a coarse tier-scaled activation model.
   Microseconds; no jax import on the hot path.
2. **registry** (warm signature) — when ``telemetry.costs`` holds a
   compiled artifact for this mesh (and remat tier, per the r10 stamp),
   its measured XLA ``temp_size_in_bytes`` replaces the analytic
   activation term.
3. **lowering** (cold, offline) — a real AOT lowering via
   :mod:`mxnet_tpu.memory.lowering` (the scale_proof engine), or a
   committed ``*_LOWER_*.json`` artifact read back through
   :func:`plan_from_artifact` when offline TPU lowering is unavailable
   (libtpu lockfile / CI).  XLA's own memory analysis is then the
   load-bearing number — this is how the Mixtral dp2 overflow
   (``MIXTRAL_DP2_OVERFLOW_r05.json``, 16.09 GiB on a 15.75 GiB
   budget) is rejected pre-compile today.

The verdict is a :class:`Plan`: fit / no-fit against the device budget
with headroom and the top offending buffers named.  ``annotate_oom``
turns the last plan into a prescription via :func:`prescribe`.
"""
import math
import os

import numpy as np

from .lowering import TPU_BUDGET_GIB

#: usable-HBM budgets by accelerator generation (GiB).  v5e is the
#: compiler-enforced figure from the committed TPU lowerings; the rest
#: follow the same usable-fraction convention.  Unknown device kinds
#: (CPU CI) fall back to 16 GiB so CPU-mesh plans stay comparable to
#: the historical scale_proof budget.
DEVICE_BUDGET_GIB = {
    "v5e": TPU_BUDGET_GIB,
    "v5p": 93.0,
    "v4": 31.0,
    "v6e": 31.25,
}
_DEFAULT_BUDGET_GIB = 16.0

_budget_override = None
_last_plan = None
_last_prescription = None


def set_budget(nbytes):
    """Override the device budget (tests shrink it to force the auto
    policy up the tier ladder).  ``None`` restores device detection."""
    global _budget_override
    _budget_override = None if nbytes is None else int(nbytes)


def budget_bytes(device_kind=None):
    """Per-device budget in bytes: explicit override >
    ``MXNET_HBM_BUDGET`` env > device-kind table > 16 GiB default."""
    if _budget_override is not None:
        return _budget_override
    env = os.environ.get("MXNET_HBM_BUDGET")
    if env:
        return int(float(env))
    if device_kind is None:
        try:
            from ..telemetry import costs

            device_kind = costs.device_kind() or ""
        except Exception:
            device_kind = ""
    kind = str(device_kind).lower()
    for key, gib in DEVICE_BUDGET_GIB.items():
        if key in kind:
            return int(gib * 2 ** 30)
    return int(_DEFAULT_BUDGET_GIB * 2 ** 30)


class Plan:
    """A pre-dispatch fit verdict for one configuration."""

    __slots__ = ("predicted_peak_bytes", "budget_bytes", "fits",
                 "headroom_bytes", "breakdown", "top_buffers", "source",
                 "remat", "offload", "ctx")

    def __init__(self, predicted_peak_bytes, budget, breakdown,
                 top_buffers, source, remat, offload, ctx=None):
        self.predicted_peak_bytes = int(predicted_peak_bytes)
        self.budget_bytes = int(budget)
        self.fits = self.predicted_peak_bytes <= self.budget_bytes
        self.headroom_bytes = self.budget_bytes - self.predicted_peak_bytes
        self.breakdown = dict(breakdown)
        self.top_buffers = list(top_buffers)
        self.source = source
        self.remat = remat
        self.offload = offload
        self.ctx = ctx or {}

    def as_dict(self):
        return {
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "predicted_peak_gib": round(
                self.predicted_peak_bytes / 2 ** 30, 3),
            "budget_bytes": self.budget_bytes,
            "fits": self.fits,
            "headroom_bytes": self.headroom_bytes,
            "breakdown": self.breakdown,
            "top_buffers": self.top_buffers,
            "source": self.source,
            "remat": self.remat,
            "offload": self.offload,
        }

    def __repr__(self):
        gib = self.predicted_peak_bytes / 2 ** 30
        verdict = "fits" if self.fits else "NO FIT"
        return (f"Plan({verdict}: predicted {gib:.2f} GiB vs "
                f"{self.budget_bytes / 2 ** 30:.2f} GiB budget, "
                f"remat={self.remat!r}, offload={self.offload!r}, "
                f"source={self.source})")


def last_plan():
    return _last_plan


def last_prescription():
    return _last_prescription


def _normalize_params(params):
    """{name: (shape, dtype)} from a Block, a Parameter mapping, or a
    mapping of (shape, dtype) pairs."""
    if hasattr(params, "_collect_params_with_prefix"):
        params = params._collect_params_with_prefix()
    out = {}
    for name, p in dict(params).items():
        if isinstance(p, tuple) and len(p) == 2 and not hasattr(p, "shape"):
            shape, dtype = p
        else:
            shape, dtype = p.shape, getattr(p, "dtype", None)
        shape = tuple(int(s) for s in (shape or ()))
        assert shape and all(s > 0 for s in shape), \
            f"{name} shape not fully declared: {shape}"
        out[name] = (shape, np.dtype(dtype or np.float32))
    return out


_STATE_SLOTS = {"sgd": 1, "nag": 1, "sgld": 0, "adam": 2, "adamw": 2,
    "lamb": 2, "rmsprop": 1, "adagrad": 1, None: 0, "none": 0}


def _optimizer_desc(optimizer):
    """(name, n_state_slots, multi_precision) for a name, an Optimizer
    instance, or None (inference)."""
    if optimizer is None:
        return None, 0, False
    if isinstance(optimizer, str):
        name = optimizer.lower()
        return name, _STATE_SLOTS.get(name, 1), False
    name = type(optimizer).__name__.lower()
    n = _STATE_SLOTS.get(name, 1)
    if name in ("sgd", "nag") and not getattr(optimizer, "momentum", 0.0):
        n = 0
    return name, n, bool(getattr(optimizer, "multi_precision", False))


def _mesh_axis_sizes(mesh):
    if mesh is None:
        return {}
    shape = getattr(mesh, "shape", mesh)
    return {str(k): int(v) for k, v in dict(shape).items()}


def _shard_div(spec, axes):
    div = 1
    for entry in spec or ():
        if entry:
            for ax in (entry if isinstance(entry, (tuple, list))
                       else (entry,)):
                div *= axes.get(str(ax), 1)
    return div


#: coarse activation prior: live activation bytes per byte of
#: per-device batch input, by remat tier — a transformer-shaped default
#: used only when neither a measured ``activation_hint`` nor a warm
#: registry temp figure is available.
_ACT_MULT = {"none": 12.0, "dots": 4.0, "layer": 2.0}
#: how the tier ladder scales a measured tier-"none" activation figure
_ACT_SCALE = {"none": 1.0, "dots": 0.35, "layer": 0.15}


def _registry_workspace(axes, remat):
    """Measured XLA temp bytes for a warm signature on this mesh (and,
    when the artifact carries the r10 stamp, this remat tier)."""
    try:
        from ..telemetry import costs

        if not costs._enabled:
            return None
        best = None
        for art in costs.snapshot():
            if art.get("error"):
                continue
            mesh_shape = art.get("mesh_shape")
            if axes and mesh_shape and dict(mesh_shape) != axes:
                continue
            stamp = art.get("remat")
            if stamp is not None and stamp != remat:
                continue
            t = int(art.get("temp_bytes") or 0)
            if t and (best is None or t > best):
                best = t
        return best
    except Exception:
        return None


def plan_kv_pool(num_layers, num_kv_heads, head_dim, num_blocks,
                 block_size, dtype=np.float32, mesh=None, rules=None):
    """Per-device bytes of the serving engine's paged KV block pool:
    2 (K and V) × layers × ``num_blocks × num_kv_heads × block_size ×
    head_dim`` × itemsize, sharded the way the serving rule table
    places the pool (``layers.{i}.kv_pool`` — KV-head axis over ``tp``
    by default).  This is the serving analog of the allreduce-bytes
    planning the trainer gets: size the pool BEFORE building the
    engine, and feed the figure to :func:`plan_model` via
    ``kv_pool_bytes=`` to get a fit verdict that includes serving
    state.  Matches ``LlamaServingEngine.kv_pool_bytes()`` exactly."""
    dtype = np.dtype(dtype)
    shape = (int(num_blocks), int(num_kv_heads), int(block_size),
             int(head_dim))
    div = 1
    if mesh is not None:
        from ..parallel import partition as pt

        axes = _mesh_axis_sizes(mesh)
        specs = pt.as_rules(rules if rules is not None
                            else "llama_serving").specs(
            {"layers.0.kv_pool": shape}, mesh)
        div = _shard_div(specs.get("layers.0.kv_pool"), axes)
    n_elem = int(np.prod(shape))
    return 2 * int(num_layers) * _ceil_div(n_elem * dtype.itemsize, div)


def plan_model(params, mesh=None, rules=None, optimizer=None,
               batch_bytes=0, remat="none", offload=None,
               activation_hint=None, budget=None, device_kind=None,
               training=True, use_registry=True, record=True,
               kv_pool_bytes=0):
    """Analytic per-device peak for a model configuration.

    ``params``: a Block / Parameter mapping / ``{name: (shape, dtype)}``.
    ``batch_bytes``: GLOBAL per-step input bytes (divided over the dp
    axis).  ``activation_hint``: measured live-activation bytes at tier
    "none" (scaled down the ladder); otherwise a warm costs-registry
    temp figure or a coarse batch-proportional prior is used.
    ``offload="host"`` moves optimizer state + f32 masters off-device.
    ``kv_pool_bytes``: per-device serving KV pool (from
    :func:`plan_kv_pool`) held live for the server's lifetime.
    """
    from .policy import normalize

    remat = normalize(remat)
    if remat == "auto":
        raise ValueError("plan_model plans ONE tier; use policy.auto_tier")
    if offload not in (None, "host"):
        raise ValueError(f"unknown offload {offload!r}")
    shapes = _normalize_params(params)
    axes = _mesh_axis_sizes(mesh)
    opt_name, n_state, multi_precision = _optimizer_desc(optimizer)

    specs = {}
    if rules is not None and mesh is not None:
        from ..parallel import partition as pt

        specs = pt.as_rules(rules).specs(
            {n: s for n, (s, _) in shapes.items()}, mesh)

    per_param = {}
    params_b = grads_b = state_b = masters_b = 0
    for name, (shape, dtype) in shapes.items():
        n_elem = int(np.prod(shape))
        div = _shard_div(specs.get(name), axes)
        p_b = _ceil_div(n_elem * dtype.itemsize, div)
        contrib = {"params": p_b}
        params_b += p_b
        if training:
            grads_b += p_b
            contrib["grads"] = p_b
            low_p = dtype.name in ("float16", "bfloat16")
            state_dt = 4 if low_p else dtype.itemsize
            s_b = n_state * _ceil_div(n_elem * state_dt, div)
            m_b = (_ceil_div(n_elem * 4, div)
                   if (low_p and multi_precision) else 0)
            state_b += s_b
            masters_b += m_b
            if s_b:
                contrib["optimizer_state"] = s_b
            if m_b:
                contrib["masters"] = m_b
        per_param[name] = contrib

    dp = axes.get("dp", 1)
    batch_b = _ceil_div(int(batch_bytes), dp)

    source = "analytic"
    if activation_hint is not None:
        act_b = int(activation_hint * _ACT_SCALE[remat])
        source = "analytic+hint"
    else:
        reg = _registry_workspace(axes, remat) if use_registry else None
        if reg is not None:
            act_b = reg
            source = "registry"
        else:
            act_b = int(batch_b * _ACT_MULT[remat]) if training else \
                int(batch_b * _ACT_MULT["none"] / 2)

    offload_b = 0
    if offload == "host":
        offload_b = state_b + masters_b
        state_b = masters_b = 0

    kv_b = int(kv_pool_bytes)
    breakdown = {
        "params": params_b, "grads": grads_b,
        "optimizer_state": state_b, "masters": masters_b,
        "batch": batch_b, "activations": act_b,
        "host_offloaded": offload_b,
    }
    if kv_b:
        breakdown["kv_pool"] = kv_b
    peak = params_b + grads_b + state_b + masters_b + batch_b + act_b \
        + kv_b

    top = sorted(
        ([{"name": n, "bytes": sum(c.values()), "components": c}
          for n, c in per_param.items()]
         + ([{"name": "<batch>", "bytes": batch_b,
              "components": {"batch": batch_b}}] if batch_b else [])
         + ([{"name": "<activations>", "bytes": act_b,
              "components": {"activations": act_b}}] if act_b else [])
         + ([{"name": "<kv_pool>", "bytes": kv_b,
              "components": {"kv_pool": kv_b}}] if kv_b else [])),
        key=lambda d: -d["bytes"])[:8]

    plan = Plan(
        peak, budget if budget is not None else budget_bytes(device_kind),
        breakdown, top, source, remat, offload,
        ctx={"shapes": shapes, "mesh": mesh, "rules": rules,
             "optimizer": optimizer, "batch_bytes": int(batch_bytes),
             "activation_hint": activation_hint, "budget": budget,
             "training": training, "device_kind": device_kind,
             "kv_pool_bytes": kv_b,
             "optimizer_desc": (opt_name, n_state, multi_precision)})
    if record:
        global _last_plan
        _last_plan = plan
    return plan


def _ceil_div(a, b):
    return int(math.ceil(a / b)) if b > 1 else int(a)


def plan_from_artifact(artifact, budget=None, record=True):
    """A :class:`Plan` from a committed lowering artifact (a
    ``scale_proof`` JSON path or dict) — the offline cold path when a
    fresh TPU lowering is unavailable.  XLA's per-device memory
    analysis is the load-bearing number: predicted peak = args + temp
    (the same upper bound every ``fit_verdict`` since r4 records)."""
    import json

    name = None
    if isinstance(artifact, (str, os.PathLike)):
        name = os.path.basename(str(artifact))
        with open(artifact) as f:
            artifact = json.load(f)
    mem = artifact.get("xla_memory_analysis_per_device", {})
    if "argument_size_in_bytes" not in mem:
        raise ValueError(f"artifact {name or '<dict>'} carries no XLA "
                         "memory analysis")
    args_b = int(mem["argument_size_in_bytes"])
    temp_b = int(mem.get("temp_size_in_bytes", 0))
    peak = args_b + temp_b
    backend = artifact.get("backend", "cpu")
    if backend == "cpu":
        peak -= int(artifact.get("fit_verdict", {}).get(
            "cpu_bf16_upcast_artifact_bytes", 0))
    if budget is None:
        budget = (int(TPU_BUDGET_GIB * 2 ** 30) if backend == "tpu"
                  else budget_bytes())
    breakdown = {"arguments": args_b, "temp": temp_b,
                 "output": int(mem.get("output_size_in_bytes", 0)),
                 "alias": int(mem.get("alias_size_in_bytes", 0))}
    top = [{"name": "<xla arguments>", "bytes": args_b,
            "components": {"arguments": args_b}},
           {"name": "<xla temp>", "bytes": temp_b,
            "components": {"temp": temp_b}}]
    plan = Plan(peak, budget, breakdown, top,
                source=f"lowering:{name or backend}",
                remat=artifact.get("remat"), offload=None,
                ctx={"artifact": name, "mesh": artifact.get("mesh"),
                     "per_chip_batch": artifact.get("per_chip_batch"),
                     "optimizer": artifact.get("optimizer")})
    if record:
        global _last_plan
        _last_plan = plan
    return plan


def prescribe(plan=None, margin=0.0):
    """Turn a failed (or failing) plan into the cheapest fix that fits:
    re-plan the next remat tiers, host offload, and a halved batch, in
    increasing cost-of-fix order.  Returns ``{"candidates": [...],
    "recommendation": {...}|None}`` or ``None`` when there is nothing
    to re-plan (no analytic plan context)."""
    from .policy import TIERS

    plan = plan if plan is not None else _last_plan
    if plan is None or "shapes" not in plan.ctx:
        return None
    ctx = plan.ctx
    base = dict(params=ctx["shapes"], mesh=ctx["mesh"],
                rules=ctx["rules"], optimizer=ctx["optimizer"],
                batch_bytes=ctx["batch_bytes"],
                activation_hint=ctx["activation_hint"],
                budget=ctx["budget"], training=ctx["training"],
                device_kind=ctx["device_kind"],
                kv_pool_bytes=ctx.get("kv_pool_bytes", 0), record=False)

    tier_i = TIERS.index(plan.remat) if plan.remat in TIERS else 0
    candidates = []
    for tier in TIERS[tier_i + 1:]:
        candidates.append((f'remat="{tier}"',
                           dict(base, remat=tier, offload=plan.offload)))
    if plan.offload != "host":
        candidates.append(('offload="host"',
                           dict(base, remat=plan.remat, offload="host")))
        if tier_i + 1 < len(TIERS):
            candidates.append(
                (f'remat="{TIERS[-1]}" + offload="host"',
                 dict(base, remat=TIERS[-1], offload="host")))
    candidates.append(
        ("halve the batch",
         dict(base, remat=plan.remat, offload=plan.offload,
              batch_bytes=ctx["batch_bytes"] // 2,
              activation_hint=(None if ctx["activation_hint"] is None
                               else ctx["activation_hint"] // 2))))

    out, rec = [], None
    for change, kw in candidates:
        cand = plan_model(**kw)
        fits = cand.predicted_peak_bytes <= cand.budget_bytes * (1 - margin)
        entry = {"change": change,
                 "predicted_peak_bytes": cand.predicted_peak_bytes,
                 "predicted_peak_gib": round(
                     cand.predicted_peak_bytes / 2 ** 30, 3),
                 "fits": fits,
                 "headroom_bytes": cand.headroom_bytes}
        out.append(entry)
        if fits and rec is None:
            rec = entry
    result = {"failing_plan": plan.as_dict(), "candidates": out,
              "recommendation": rec}
    global _last_prescription
    _last_prescription = result
    return result
