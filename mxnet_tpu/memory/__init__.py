"""Memory as a managed budget: planner + auto-remat + host offload.

PR 5 made memory *observable* (the memwatch ledger and the
per-signature XLA memory analysis in ``telemetry.costs``); this package
makes it *actionable* — the policy layer of MXNet 1.x's graph-executor
memory planner, rebuilt for the XLA world:

- :mod:`.planner` — pre-dispatch per-device peak prediction and
  fit/no-fit verdicts against the device budget (15.75 GiB on v5e);
- :mod:`.policy` — the remat tier ladder (none → dots → layer) and the
  auto policy that picks the cheapest tier that fits;
- :mod:`.offload` — host-resident optimizer state behind
  ``Trainer(offload="host")``;
- :mod:`.lowering` — the offline AOT-lowering engine (extracted from
  ``tools/scale_proof.py``, which now consumes it).

``telemetry.step_end`` and ``memwatch.write_postmortem`` probe this
module via ``sys.modules`` — importing it is what turns on the JSONL
fields and the OOM prescription; nothing here runs on the step hot
path otherwise.  See docs/memory.md.
"""
from . import lowering, offload, planner, policy
from .planner import (Plan, budget_bytes, last_plan, plan_from_artifact,
                      plan_kv_pool, plan_model, prescribe, set_budget)
from .policy import TIERS, auto_tier, checkpoint_wrap, select_tier

__all__ = [
    "Plan", "TIERS", "auto_tier", "budget_bytes", "checkpoint_wrap",
    "last_plan", "lowering", "offload", "plan_from_artifact",
    "plan_kv_pool", "plan_model", "planner", "policy", "prescribe",
    "select_tier",
    "set_budget", "telemetry_fields",
]


def telemetry_fields():
    """The per-step JSONL fields this package contributes (probed by
    ``telemetry.step_end``; keys appear only once the corresponding
    mechanism has actually been used)."""
    out = {}
    pol = policy.last_policy()
    if pol is not None:
        out["remat_policy"] = pol["tier"]
    plan = planner.last_plan()
    if plan is not None:
        out["predicted_peak_bytes"] = plan.predicted_peak_bytes
    off = offload.resident_bytes()
    if off:
        out["offload_bytes"] = off
    return out
