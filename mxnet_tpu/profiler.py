"""Profiler façade.

Reference: ``src/profiler/profiler.{h,cc}:?`` + ``python/mxnet/profiler.py:?``
— engine workers wrap each operation with profiler events when enabled;
output is chrome://tracing JSON plus aggregate per-op tables
(``mx.profiler.dumps()``); env autostart ``MXNET_PROFILER_AUTOSTART``
(SURVEY §5).

TPU-native redesign: two layers of instrumentation.
(1) Host-side op-dispatch events recorded by ``ops.registry.apply_op`` via
    the ``record_op_event`` hook here — the analog of engine opr events —
    written as chrome://tracing JSON by ``dump()`` and aggregated by
    ``dumps()``.  Dispatch wall-time is what the host controls; device-side
    timing belongs to XLA, hence:
(2) ``jax.profiler`` (TensorBoard/XPlane trace) started/stopped with the
    profiler state when ``profile_device_trace`` is set — this is where
    MXU/HBM utilisation actually shows up.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict

from .base import MXNetError

_lock = threading.Lock()
_config = {
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "profile_device_trace": False,
    "filename": "profile.json",
    "aggregate_stats": False,
}
_state = "stop"
_events = []          # chrome trace events
_agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # n, tot, min, max
_t0 = None
_jax_trace_dir = None


def set_config(**kwargs):
    """Configure (reference ``profiler.set_config``): accepts the reference
    kwargs (``profile_all``, ``profile_symbolic``, ``profile_imperative``,
    ``profile_memory``, ``profile_api``, ``filename``,
    ``aggregate_stats``) plus ``profile_device_trace`` for the XLA/
    TensorBoard trace."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError(f"unknown profiler config keys {sorted(unknown)}")
    _config.update(kwargs)


def set_state(state="stop"):
    """'run' starts event collection; 'stop' ends it (reference
    ``profiler.set_state``)."""
    global _state, _t0, _jax_trace_dir
    if state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    if state == "run" and _state != "run":
        # keep the original epoch across pause/resume so chrome-trace
        # timestamps stay monotonic within one profile
        if _t0 is None:
            _t0 = time.perf_counter()
        if _config["profile_device_trace"]:
            import jax

            _jax_trace_dir = os.path.splitext(_config["filename"])[0] \
                + "_xla_trace"
            jax.profiler.start_trace(_jax_trace_dir)
    if state == "stop" and _state == "run":
        if _jax_trace_dir is not None:
            import jax

            jax.profiler.stop_trace()
            _jax_trace_dir = None
    _state = state


def is_running():
    return _state == "run"


def pause(profile_process="worker"):
    set_state("stop")


def resume(profile_process="worker"):
    set_state("run")


def record_op_event(name, dur_s, cat="operator"):
    """Called from the op dispatch path (ops/registry.apply_op) — the
    analog of engine workers wrapping opr execution with profiler events."""
    if _state != "run":
        return
    with _lock:
        ts = (time.perf_counter() - _t0) * 1e6
        _events.append({
            "name": name, "cat": cat, "ph": "X",
            "ts": ts - dur_s * 1e6, "dur": dur_s * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
        })
        a = _agg[name]
        a[0] += 1
        a[1] += dur_s * 1e3
        a[2] = min(a[2], dur_s * 1e3)
        a[3] = max(a[3], dur_s * 1e3)


def record_span_event(name, start_s, dur_s, cat="telemetry", args=None):
    """Mirror a completed telemetry span into the chrome-trace buffer
    (and the aggregate table) so trainer-phase spans and op-dispatch
    events share one timeline.  ``start_s`` is the span's
    ``time.perf_counter()`` entry stamp — same timebase as ``_t0``."""
    if _state != "run":
        return
    with _lock:
        if _t0 is None:
            return
        event = {
            "name": name, "cat": cat, "ph": "X",
            "ts": (start_s - _t0) * 1e6, "dur": dur_s * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
        }
        if args:
            event["args"] = {k: str(v) for k, v in args.items()}
        _events.append(event)
        a = _agg[name]
        a[0] += 1
        a[1] += dur_s * 1e3
        a[2] = min(a[2], dur_s * 1e3)
        a[3] = max(a[3], dur_s * 1e3)


def record_counter_event(name, values, ts_s=None):
    """Chrome-trace counter sample (``"ph": "C"``): Perfetto/chrome
    render one stacked counter track per ``name``, with one series per
    key of ``values`` (a dict series-name -> number).  Used by
    ``telemetry.memwatch`` to plot live device bytes alongside the span
    timeline.  ``ts_s`` is an optional ``time.perf_counter()`` stamp."""
    if _state != "run":
        return
    with _lock:
        if _t0 is None:
            return
        stamp = time.perf_counter() if ts_s is None else ts_s
        _events.append({
            "name": name, "cat": "memory", "ph": "C",
            "ts": (stamp - _t0) * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
            "args": {k: float(v) for k, v in values.items()},
        })


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON to ``filename`` (reference
    ``profiler.dump``).  ``finished=True`` ends the profile: the event
    buffer and epoch reset so a later run starts a fresh trace."""
    global _t0
    if finished:
        set_state("stop")
    with _lock:
        payload = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
        if finished:
            _events.clear()
            _t0 = None
    with open(_config["filename"], "w") as f:
        json.dump(payload, f)


def dumps(reset=False, format="table"):
    """Aggregate per-event stats (reference ``profiler.dumps`` with
    ``aggregate_stats=True``).  ``format="table"`` (default) returns the
    fixed-width text table; ``format="json"`` returns a JSON object
    string mapping event name -> {count, total_ms, min_ms, max_ms,
    avg_ms} for machine consumption."""
    if format not in ("table", "json"):
        raise MXNetError(
            f"unknown dumps format {format!r}; expected 'table' or 'json'")
    with _lock:
        rows = sorted(_agg.items(), key=lambda kv: -kv[1][1])
        if format == "json":
            payload = {
                name: {"count": n, "total_ms": tot, "min_ms": mn,
                       "max_ms": mx, "avg_ms": tot / max(n, 1)}
                for name, (n, tot, mn, mx) in rows}
            out = json.dumps(payload)
        else:
            lines = [f"{'Name':<40}{'Total Count':>12}{'Total(ms)':>12}"
                     f"{'Min(ms)':>10}{'Max(ms)':>10}{'Avg(ms)':>10}"]
            for name, (n, tot, mn, mx) in rows:
                lines.append(f"{name[:39]:<40}{n:>12}{tot:>12.3f}"
                             f"{mn:>10.3f}{mx:>10.3f}"
                             f"{tot / max(n, 1):>10.3f}")
            out = "\n".join(lines)
        if reset:
            _agg.clear()  # aggregate stats only; dump() still sees events
    return out


class Scope:
    """Named profiling scope (reference ``profiler.Scope`` context
    manager): ops dispatched inside are prefixed ``name:op``."""

    _current = threading.local()

    def __init__(self, name="<unk>:"):
        self._name = name if name.endswith(":") else name + ":"
        self._old = None

    def __enter__(self):
        self._old = getattr(Scope._current, "value", None)
        Scope._current.value = self._name
        return self

    def __exit__(self, *exc):
        Scope._current.value = self._old


def current_scope_prefix():
    return getattr(Scope._current, "value", None) or ""


class Marker:
    """Instant marker event (reference ``profiler.Marker``)."""

    def __init__(self, name, scope="process"):
        self._name = name
        self._scope = scope

    def mark(self, scope=None):
        if _state != "run":
            return
        with _lock:
            _events.append({
                "name": self._name, "ph": "i",
                "ts": (time.perf_counter() - _t0) * 1e6,
                "s": {"process": "p", "thread": "t",
                      "global": "g"}.get(scope or self._scope, "p"),
                "pid": os.getpid(),
                "tid": threading.get_ident() % 100000,
            })


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    set_config(profile_all=True)
    set_state("run")
