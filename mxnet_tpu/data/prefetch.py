"""Double-buffered host→device prefetch: overlap ``device_put`` with
the in-flight step.

The trainer's step N runs on device while this module's background
thread moves step N+1's batch host→device (sharded along dp via
``parallel.shard_batch`` when a mesh is active) and *waits for the
transfer to land* — so when the trainer asks for the next batch, the
arrays are already resident and ``get()`` returns immediately.  The
consumer-side blocked time is accounted as the ``data.wait_ms`` counter
(surfaced as the top-level ``data_wait_ms`` JSONL field): an input-bound
job shows it climbing toward the step time, a compute-bound one shows
p50 ≈ 0 (the r14 acceptance bar, proven in ``DATA_PLANE_r14.json``).

``_prefetch`` is this module's sanctioned materialize site (mxlint
MATERIALIZE_DEFS): the ``block_until_ready`` inside it is the entire
point — without it the "prefetched" batch would just be a queued
transfer that lands lazily on first use, i.e. inside the step we are
trying to keep fed.  It runs on the prefetch thread, never in a trace.

Overlap evidence: the prefetcher registers an engine dispatch callback
and counts ``data.overlap_dispatch`` whenever a compute segment is
dispatched while a transfer is in flight — direct proof the two were
concurrent rather than serialized.
"""
from __future__ import annotations

import queue
import threading
import time

from .. import engine, telemetry
from ..base import MXNetError
from .packing import PackedBatch

__all__ = ["DevicePrefetcher"]

_SENTINEL = object()


def _iter_leaves(batch):
    """Yield every array leaf of a batch pytree."""
    if isinstance(batch, PackedBatch):
        yield from (batch.tokens, batch.segment_ids, batch.labels,
                    batch.loss_mask)
    elif isinstance(batch, dict):
        for v in batch.values():
            yield from _iter_leaves(v)
    elif isinstance(batch, (list, tuple)):
        for v in batch:
            yield from _iter_leaves(v)
    else:
        yield batch


def _map_leaves(fn, batch):
    """Apply ``fn`` to every array leaf of a batch pytree (dict, tuple,
    list, PackedBatch, or a bare array)."""
    if isinstance(batch, PackedBatch):
        return PackedBatch(fn(batch.tokens), fn(batch.segment_ids),
                           fn(batch.labels), fn(batch.loss_mask))
    if isinstance(batch, dict):
        return {k: _map_leaves(fn, v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return type(batch)(_map_leaves(fn, v) for v in batch)
    return fn(batch)


class DevicePrefetcher:
    """Pull host batches from ``source``, land them on device ahead of
    the consumer, hand them out in order.

    Parameters
    ----------
    source : iterator
        Yields host batches (numpy pytrees or ``PackedBatch``) in step
        order.  Exhaustion ends the stream; an exception in the source
        is re-raised at the consumer's next ``get()``.
    depth : int
        Max device batches resident ahead of the consumer.  2 = classic
        double buffering (one being consumed, one in flight).
    mesh : jax Mesh, optional
        When given (or a ``parallel`` mesh is active), leaves are placed
        with ``parallel.shard_batch`` along ``axis_name``; otherwise a
        plain single-device put.
    axis_name : str
        Mesh axis the batch dimension shards over (default ``"dp"``).
    """

    def __init__(self, source, depth=2, mesh=None, axis_name="dp"):
        if depth < 1:
            raise MXNetError("prefetch depth must be >= 1")
        self._source = iter(source)
        self._depth = int(depth)
        self._mesh = mesh
        self._axis_name = axis_name
        self._q = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._transfer_inflight = threading.Event()
        self._started = False
        self._closed = False
        self._thread = threading.Thread(target=self._prefetch,
                                        name="mxt-data-prefetch",
                                        daemon=True)
        engine.register_dispatch_callback(self._on_dispatch)

    # -- producer side -------------------------------------------------------

    def _put_device(self, arr):
        from .. import nd, parallel

        mesh = self._mesh
        if mesh is None and parallel.is_initialized():
            mesh = parallel.current_mesh()
        if mesh is not None:
            return parallel.shard_batch(arr, mesh,
                                        axis_name=self._axis_name)
        return nd.array(arr)

    def _prefetch(self):
        """Background transfer loop — the data plane's designated
        materialize site (mxlint MATERIALIZE_DEFS): each batch is placed
        on device and THEN waited on, so by the time it reaches the
        queue the transfer has landed and the consumer never inherits
        a lazy copy inside its step."""
        try:
            while not self._stop.is_set():
                try:
                    host = next(self._source)
                except StopIteration:
                    break
                self._transfer_inflight.set()
                try:
                    dev = _map_leaves(self._put_device, host)
                    for leaf in _iter_leaves(dev):
                        leaf._data.block_until_ready()
                finally:
                    self._transfer_inflight.clear()
                while not self._stop.is_set():
                    try:
                        self._q.put(("ok", dev), timeout=0.1)
                        break
                    except queue.Full:
                        continue
            while not self._stop.is_set():
                try:
                    self._q.put(("end", _SENTINEL), timeout=0.1)
                    break
                except queue.Full:
                    continue
        except BaseException as exc:  # surfaced at the consumer's get()
            try:
                self._q.put(("err", exc), timeout=1.0)
            except queue.Full:
                pass

    def _on_dispatch(self, reason):
        if self._transfer_inflight.is_set():
            telemetry.count("data.overlap_dispatch")

    # -- consumer side -------------------------------------------------------

    def get(self, timeout=None):
        """Next device batch in step order.  Blocked time (the trainer
        starving on input) is accounted as ``data.wait_ms``; a fully
        overlapped pipeline spends ~0 here."""
        if self._closed:
            raise MXNetError("DevicePrefetcher is closed")
        if not self._started:
            self._started = True
            self._thread.start()
        t0 = time.perf_counter()
        try:
            kind, payload = self._q.get(timeout=timeout)
        except queue.Empty:
            raise MXNetError(
                f"DevicePrefetcher timed out after {timeout}s waiting "
                "for the next batch")
        telemetry.count("data.wait_ms",
                        (time.perf_counter() - t0) * 1e3)
        telemetry.gauge("data.prefetch_depth", self._q.qsize())
        if kind == "err":
            self.close()
            raise payload
        if kind == "end":
            self.close()
            raise StopIteration
        return payload

    def __iter__(self):
        return self

    def __next__(self):
        return self.get()

    def close(self):
        """Stop the transfer thread and release the engine hook."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        engine.unregister_dispatch_callback(self._on_dispatch)
        if self._started:
            # unblock a producer stuck on a full queue
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
