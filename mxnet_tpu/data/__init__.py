"""The streaming data plane (r14): sharded elastic readers, overlapped
host→device prefetch, and sequence packing.

Composition (docs/data.md):

    ShardedRecordReader ──► StreamingLoader ──► DevicePrefetcher ──► step
        (.rec/.idx,             (decode workers,      (double-buffered
         elastic draw)           optional packing)     sharded device_put)

Everything is keyed on the global training step: the reader's sample
draw is a pure function of ``(seed, step)`` through ``mxnet_tpu.
elastic``, so the checkpointed step fully determines the pipeline
position at any world size — the same elastic contract the trainer
already holds, now extended to real record files.
"""
from .reader import ShardedRecordReader
from .packing import (PackedBatch, PackingStats, SequencePacker,
                      pack_documents)
from .prefetch import DevicePrefetcher
from .pipeline import StreamingLoader

__all__ = ["ShardedRecordReader", "StreamingLoader", "DevicePrefetcher",
           "SequencePacker", "PackedBatch", "PackingStats",
           "pack_documents"]
