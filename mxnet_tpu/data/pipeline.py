"""StreamingLoader: the step-keyed pipeline tying the data plane
together.

reader (``.rec`` shards) → multi-worker decode/transform → optional
sequence packing → ``DevicePrefetcher`` (sharded device_put overlapped
with the in-flight step).

The loader is **step-keyed, not epoch-keyed**: batch N is a pure
function of ``(seed, step=N)`` through ``elastic``, so resuming from a
checkpoint at step S is just ``StreamingLoader(..., start_step=S)`` —
there is no sampler state to save, and a job resumed at a different
world size replays the identical global batch sequence
(``tests/test_data_plane.py`` proves the 2→1→2 contract through this
exact class).

Two modes:

- **sample mode** (``transform=``): each step draws THIS RANK's slice
  of the global batch, decodes each record with ``transform(raw_bytes)``
  on the worker threads, and stacks the samples;
- **packed mode** (``packer=`` + ``tokenize=``): each step decodes the
  FULL global draw (every rank tokenizes the same documents — the cost
  of rank-independent determinism), packs it with the shared
  ``SequencePacker``, then keeps this rank's contiguous row slice via
  ``elastic.shard_rows``.
"""
from __future__ import annotations

import threading

import numpy as np

from .. import elastic
from ..base import MXNetError
from .prefetch import DevicePrefetcher

__all__ = ["StreamingLoader"]


def _default_batchify(samples):
    """Stack decoded samples into one host batch (tuple samples →
    tuple of stacked arrays, the Gluon (data, label) convention)."""
    s0 = samples[0]
    if isinstance(s0, (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples])
                     for i in range(len(s0)))
    return np.stack([np.asarray(s) for s in samples])


class StreamingLoader:
    """Elastic streaming loader over a ``ShardedRecordReader``.

    Parameters
    ----------
    reader : ShardedRecordReader
        Supplies raw record bytes + the deterministic (seed, step)
        index draw.
    transform : callable, optional
        ``raw_bytes -> sample`` decode for sample mode.
    packer : SequencePacker, optional
        Enables packed mode (requires ``tokenize``).  The packer's
        ``stats`` accumulate across the stream.
    tokenize : callable, optional
        ``raw_bytes -> 1-D int token array`` for packed mode.
    batchify : callable, optional
        Sample-mode stacking override (default stacks with np.stack).
    num_workers : int
        Decode worker threads; 0 decodes inline on the prefetch thread.
    prefetch_depth : int
        Device batches resident ahead of the consumer (2 = double
        buffer).
    mesh : jax Mesh, optional
        dp-shard placement for the device put (defaults to the active
        ``parallel`` mesh, if any).
    start_step, num_steps
        First step to emit and how many (None = endless stream).
    world_size, rank
        Override the live ``elastic.world_info`` (tests).
    """

    def __init__(self, reader, *, transform=None, packer=None,
                 tokenize=None, batchify=None, num_workers=2,
                 prefetch_depth=2, mesh=None, start_step=0,
                 num_steps=None, world_size=None, rank=None):
        if packer is not None and tokenize is None:
            raise MXNetError("packed mode needs tokenize= (raw bytes -> "
                             "1-D int token array)")
        if packer is None and transform is None:
            raise MXNetError("need transform= (sample mode) or "
                             "packer= + tokenize= (packed mode)")
        self._reader = reader
        self._transform = transform
        self._packer = packer
        self._tokenize = tokenize
        self._batchify = batchify or _default_batchify
        self._num_workers = max(0, int(num_workers))
        self._prefetch_depth = max(1, int(prefetch_depth))
        self._start_step = int(start_step)
        self._num_steps = None if num_steps is None else int(num_steps)
        if world_size is None or rank is None:
            r, w = elastic.world_info()
            rank = r if rank is None else rank
            world_size = w if world_size is None else world_size
        self._world, self._rank = int(world_size), int(rank)
        self._stop = threading.Event()
        self._threads = []
        self._prefetcher = DevicePrefetcher(self._host_batches(),
                                            depth=self._prefetch_depth,
                                            mesh=mesh)

    @property
    def packing_stats(self):
        return self._packer.stats if self._packer is not None else None

    # -- host-side assembly --------------------------------------------------

    def _build_host_batch(self, step):
        if self._packer is not None:
            # every rank decodes + packs the SAME global draw (packing
            # must be rank-independent for elastic parity), then keeps
            # its contiguous row slice
            idxs = self._reader.global_indices_for_step(step)
            docs = [self._tokenize(self._reader.read(i)) for i in idxs]
            batch = self._packer.pack(docs)
            rows = elastic.shard_rows(self._packer.batch_size,
                                      self._world, self._rank)
            return batch.rows(rows)
        idxs = self._reader.batch_indices_for_step(step, self._world,
                                                   self._rank)
        return self._batchify(
            [self._transform(self._reader.read(i)) for i in idxs])

    def _host_batches(self):
        """Ordered host-batch generator: ``num_workers`` threads decode
        steps ahead inside a bounded window, the generator yields them
        in step order (the DataLoader's order-restoration shape)."""
        end = (None if self._num_steps is None
               else self._start_step + self._num_steps)
        if self._num_workers == 0:
            step = self._start_step
            while (end is None or step < end) and \
                    not self._stop.is_set():
                yield self._build_host_batch(step)
                step += 1
            return

        results = {}
        cond = threading.Condition()
        next_fetch = [self._start_step]
        consumed = [self._start_step]
        errors = []
        window = self._prefetch_depth + self._num_workers
        stop = self._stop

        def worker():
            while True:
                with cond:
                    while (not stop.is_set() and not errors and
                           (end is None or next_fetch[0] < end) and
                           next_fetch[0] - consumed[0] >= window):
                        cond.wait(0.1)
                    if stop.is_set() or errors or \
                            (end is not None and next_fetch[0] >= end):
                        return
                    step = next_fetch[0]
                    next_fetch[0] += 1
                try:
                    batch = self._build_host_batch(step)
                except BaseException as exc:
                    with cond:
                        errors.append(exc)
                        cond.notify_all()
                    return
                with cond:
                    results[step] = batch
                    cond.notify_all()

        self._threads = [threading.Thread(target=worker,
                                          name=f"mxt-data-decode-{i}",
                                          daemon=True)
                         for i in range(self._num_workers)]
        for t in self._threads:
            t.start()
        step = self._start_step
        try:
            while end is None or step < end:
                with cond:
                    while step not in results and not errors and \
                            not stop.is_set():
                        cond.wait(0.1)
                    if errors:
                        raise errors[0]
                    if stop.is_set():
                        return
                    batch = results.pop(step)
                    consumed[0] = step + 1
                    cond.notify_all()
                yield batch
                step += 1
        finally:
            stop.set()
            with cond:
                cond.notify_all()
            for t in self._threads:
                t.join(timeout=5)

    # -- consumer API --------------------------------------------------------

    def get(self, timeout=None):
        """Next device-resident batch for this rank, in step order."""
        return self._prefetcher.get(timeout=timeout)

    def __iter__(self):
        return self

    def __next__(self):
        return self._prefetcher.get()

    def close(self):
        self._stop.set()
        self._prefetcher.close()
        for t in self._threads:
            t.join(timeout=5)
        self._reader.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
