"""Sharded streaming readers over ``.rec``/``.idx`` shard sets.

The elastic data contract (``docs/fault_tolerance.md``) says the
checkpointed step IS the data-pipeline position: sample order must be a
pure function of ``(seed, step)``, identical at every world size.  This
module extends that contract from an in-memory array to a directory of
RecordIO shards:

- the **global sample table** is the concatenation of every shard's
  ``.idx`` keys, in shard order — a stable enumeration ``0..N-1`` that
  every host derives identically from the same file set;
- ``batch_indices_for_step`` composes ``elastic.global_batch_indices``
  with ``elastic.shard_indices``, so a 2→1→2-worker resize replays the
  exact same global batches (``tests/test_data_plane.py`` proves it);
- ``read`` is random access via the ``.idx`` sidecar — a host only ever
  touches the bytes its rank draws, which is what makes the per-host
  partitioning real rather than read-everything-filter-later.

File handles are per-thread (``threading.local``): a seek+read pair on
one shared handle is not atomic, and the prefetch pipeline reads from
worker threads.
"""
from __future__ import annotations

import glob
import os
import threading

from .. import recordio
from ..base import MXNetError
from .. import elastic

__all__ = ["ShardedRecordReader"]


def _resolve_shards(path):
    """Expand ``path`` (one ``.rec``, a glob, a directory, or a list)
    into a sorted list of ``(rec, idx)`` pairs."""
    if isinstance(path, (list, tuple)):
        recs = [str(p) for p in path]
    elif os.path.isdir(path):
        recs = sorted(glob.glob(os.path.join(path, "*.rec")))
    elif any(ch in str(path) for ch in "*?["):
        recs = sorted(glob.glob(str(path)))
    else:
        recs = [str(path)]
    if not recs:
        raise MXNetError(f"no .rec shards found at {path!r}")
    pairs = []
    for rec in recs:
        idx = os.path.splitext(rec)[0] + ".idx"
        if not os.path.isfile(rec):
            raise MXNetError(f"record shard not found: {rec!r}")
        if not os.path.isfile(idx):
            raise MXNetError(
                f"missing .idx sidecar for shard {rec!r} (expected "
                f"{idx!r}; indexed random access needs it)")
        pairs.append((rec, idx))
    return pairs


class ShardedRecordReader:
    """Deterministic random-access reader over one or many RecordIO
    shards, sharded per host through ``mxnet_tpu.elastic``.

    Parameters
    ----------
    path : str or list
        A ``.rec`` file, a glob, a directory of ``*.rec``, or an
        explicit list of ``.rec`` paths.  Each shard needs its ``.idx``
        sidecar.
    batch_size : int
        GLOBAL batch size (summed over ranks); must divide evenly by
        every world size the job may run at.
    seed, shuffle
        Forwarded to ``elastic.global_batch_indices``.
    """

    def __init__(self, path, batch_size, seed=0, shuffle=True):
        self._shards = _resolve_shards(path)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        # global sample table: position -> (shard_no, key); built from
        # the .idx sidecars alone (no record payload is touched)
        self._table = []
        for shard_no, (rec, idx_path) in enumerate(self._shards):
            keys = []
            with open(idx_path) as fin:
                for lineno, line in enumerate(fin, 1):
                    stripped = line.strip()
                    if not stripped:
                        continue
                    parts = stripped.split("\t")
                    try:
                        keys.append(int(parts[0]))
                        int(parts[1])
                    except (IndexError, ValueError) as exc:
                        raise MXNetError(
                            f"corrupt index line {lineno} in "
                            f"{idx_path!r}: {stripped!r}") from exc
            if not keys:
                raise MXNetError(f"empty index {idx_path!r}")
            self._table.extend((shard_no, k) for k in keys)
        self._local = threading.local()

    def __len__(self):
        return len(self._table)

    @property
    def num_shards(self):
        return len(self._shards)

    def _handle(self, shard_no):
        """Per-thread MXIndexedRecordIO handles (seek+read is stateful)."""
        handles = getattr(self._local, "handles", None)
        if handles is None:
            handles = self._local.handles = {}
        h = handles.get(shard_no)
        if h is None:
            rec, idx = self._shards[shard_no]
            h = handles[shard_no] = recordio.MXIndexedRecordIO(
                idx, rec, "r")
        return h

    def read(self, global_idx):
        """Raw record bytes for one global sample position."""
        shard_no, key = self._table[int(global_idx)]
        return self._handle(shard_no).read_idx(key)

    def batch_indices_for_step(self, step, world_size=None, rank=None):
        """This rank's slice of the step's global batch, as global
        sample positions.  Defaults to the live ``elastic.world_info``.
        """
        if world_size is None or rank is None:
            r, w = elastic.world_info()
            rank = r if rank is None else rank
            world_size = w if world_size is None else world_size
        return elastic.shard_for_step(len(self._table), self.batch_size,
                                      step, world_size, rank,
                                      seed=self.seed, shuffle=self.shuffle)

    def global_indices_for_step(self, step):
        """The FULL global batch for a step (every rank's draw) — what
        sequence packing consumes so all ranks pack identically."""
        return elastic.global_batch_indices(
            len(self._table), self.batch_size, step, seed=self.seed,
            shuffle=self.shuffle)

    def batch_for_step(self, step, world_size=None, rank=None):
        """Payload bytes for this rank's slice of the step's batch."""
        idxs = self.batch_indices_for_step(step, world_size, rank)
        return [self.read(i) for i in idxs]

    def close(self):
        handles = getattr(self._local, "handles", None)
        if handles:
            for h in handles.values():
                h.close()
            self._local.handles = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
