"""Sequence packing: variable-length documents → one compile signature.

LLM pretraining corpora are ragged; XLA wants one ``(batch, seq_len)``
signature (every new shape is a recompile, SURVEY §2.5).  The packer
greedily first-fits each document of a step's draw into a fixed
``(batch_size, seq_len)`` grid and emits **segment ids** so attention
can keep packed documents from seeing each other — the same mask
machinery the serving slots use (``models/llama.py`` builds the
``causal & same-segment`` mask from these ids inside the traced fn).

Determinism contract: packing is a pure function of the document list
(greedy first-fit in draw order, no sorting, no RNG), so every rank that
packs the same global draw gets the identical grid and can take its row
slice via ``elastic.shard_rows`` — this is what keeps elastic 2→1→2
resizes step-for-step exact through the packed path.

Efficiency accounting (``PackingStats``): ``efficiency`` is tokens kept
over grid capacity.  The r14 acceptance bar is ≥ 0.85 on a mixed-length
corpus; the bench lane (``benchmark/input_pipeline.py --data-plane``)
records it in ``DATA_PLANE_r14.json``.
"""
from __future__ import annotations

import threading

import numpy as np

from ..base import MXNetError

__all__ = ["PackedBatch", "PackingStats", "SequencePacker",
           "pack_documents"]


class PackingStats:
    """Running token-accounting across packed batches."""

    __slots__ = ("tokens_kept", "tokens_padded", "tokens_dropped",
                 "docs_packed", "docs_dropped", "batches")

    def __init__(self):
        self.tokens_kept = 0
        self.tokens_padded = 0
        self.tokens_dropped = 0
        self.docs_packed = 0
        self.docs_dropped = 0
        self.batches = 0

    def efficiency(self):
        """Tokens kept / grid capacity (kept + padded) in [0, 1]."""
        total = self.tokens_kept + self.tokens_padded
        return self.tokens_kept / total if total else 0.0

    def merge(self, other):
        """Fold another stats object into this one (the packer merges
        per-batch locals under a lock — decode workers pack steps
        concurrently)."""
        for f in self.__slots__:
            setattr(self, f, getattr(self, f) + getattr(other, f))

    def as_dict(self):
        return {"tokens_kept": self.tokens_kept,
                "tokens_padded": self.tokens_padded,
                "tokens_dropped": self.tokens_dropped,
                "docs_packed": self.docs_packed,
                "docs_dropped": self.docs_dropped,
                "batches": self.batches,
                "efficiency": self.efficiency()}


class PackedBatch:
    """One fixed-signature packed batch.

    ``tokens``       (B, T) int32 — documents back to back, 0-padded
    ``segment_ids``  (B, T) int32 — 0 = padding, 1..n per row
    ``labels``       (B, T) int32 — next token within the same segment
    ``loss_mask``    (B, T) float32 — 1 where ``labels`` is a real
                     next-token target: padding and each segment's last
                     position are masked (no cross-document prediction)
    """

    __slots__ = ("tokens", "segment_ids", "labels", "loss_mask")

    def __init__(self, tokens, segment_ids, labels, loss_mask):
        self.tokens = tokens
        self.segment_ids = segment_ids
        self.labels = labels
        self.loss_mask = loss_mask

    @property
    def shape(self):
        return self.tokens.shape

    def rows(self, row_idx):
        """A row-sliced view (each rank keeps ``elastic.shard_rows``)."""
        r = np.asarray(row_idx)
        return PackedBatch(self.tokens[r], self.segment_ids[r],
                           self.labels[r], self.loss_mask[r])


class SequencePacker:
    """Greedy first-fit packer onto a fixed ``(batch_size, seq_len)``
    grid.

    Documents are placed in draw order into the first row with room
    (first-fit keeps the operation deterministic AND order-stable — no
    sorting, so the same draw always packs the same way).  A document
    longer than ``seq_len`` is truncated; a document that fits no row is
    dropped and counted in ``stats.tokens_dropped``.
    """

    def __init__(self, batch_size, seq_len):
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        if self.batch_size <= 0 or self.seq_len <= 0:
            raise MXNetError("batch_size and seq_len must be positive")
        self.stats = PackingStats()
        self._stats_lock = threading.Lock()

    def pack(self, documents):
        """Pack a list of 1-D int token arrays into one PackedBatch."""
        B, T = self.batch_size, self.seq_len
        tokens = np.zeros((B, T), dtype=np.int32)
        seg = np.zeros((B, T), dtype=np.int32)
        fill = np.zeros(B, dtype=np.int64)   # next free column per row
        nseg = np.zeros(B, dtype=np.int32)   # segments placed per row
        st = PackingStats()
        for doc in documents:
            d = np.asarray(doc, dtype=np.int32).ravel()
            if d.size == 0:
                continue
            if d.size > T:
                st.tokens_dropped += d.size - T
                d = d[:T]
            n = d.size
            placed = False
            for row in range(B):
                if T - fill[row] >= n:
                    c = fill[row]
                    tokens[row, c:c + n] = d
                    nseg[row] += 1
                    seg[row, c:c + n] = nseg[row]
                    fill[row] = c + n
                    st.tokens_kept += n
                    st.docs_packed += 1
                    placed = True
                    break
            if not placed:
                st.tokens_dropped += n
                st.docs_dropped += 1
        st.tokens_padded += int(B * T - fill.sum())
        st.batches += 1
        with self._stats_lock:
            self.stats.merge(st)

        # next-token labels within each segment: label[t] = tokens[t+1]
        # when t+1 is the same segment; everything else is masked out
        labels = np.zeros((B, T), dtype=np.int32)
        labels[:, :-1] = tokens[:, 1:]
        same = np.zeros((B, T), dtype=bool)
        same[:, :-1] = (seg[:, :-1] == seg[:, 1:]) & (seg[:, :-1] > 0)
        loss_mask = same.astype(np.float32)
        labels[~same] = 0
        return PackedBatch(tokens, seg, labels, loss_mask)


def pack_documents(documents, batch_size, seq_len):
    """One-shot convenience: ``(PackedBatch, PackingStats)``."""
    p = SequencePacker(batch_size, seq_len)
    batch = p.pack(documents)
    return batch, p.stats
