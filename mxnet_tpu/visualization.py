"""Network visualization: ``print_summary`` + ``plot_network``.

Reference: ``python/mxnet/visualization.py:?`` — walks the symbol-json
graph printing a layer table (name, output shape, params) and emitting a
graphviz ``Digraph`` (SURVEY §2.4 misc row).

Here the walk runs over the native ``Symbol`` node graph;
``plot_network`` emits DOT source text directly (graphviz-the-python-pkg
is not a dependency; the text renders with any dot tool).
"""
from __future__ import annotations

from .base import MXNetError


def _topo_nodes(symbol):
    return symbol._topo()


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer table for a Symbol (reference
    ``mx.viz.print_summary``).  ``shape``: dict of input name → shape for
    output-shape inference."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    shapes = {}
    if shape is not None:
        internals = symbol.get_internals()
        onames = internals.list_outputs()
        _, int_shapes, _ = internals.infer_shape(**shape)
        shapes = dict(zip(onames, int_shapes))
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(f, pos):
        line = ""
        for i, field in enumerate(f):
            line += str(field)
            line = line[:pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0
    for node in _topo_nodes(symbol):
        if node.op == "null" and not node.inputs:
            continue
        name = node.name
        out_shape = shapes.get(f"{name}_output", shapes.get(name, ""))
        nparams = 0
        for inp, _ in node.inputs:
            # param inputs by naming convention (same heuristic the
            # reference uses to split weights from data inputs)
            if inp.op == "null" and inp.name.endswith(
                    ("_weight", "_bias", "_gamma", "_beta", "_moving_mean",
                     "_moving_var")):
                s = shapes.get(inp.name)
                if s:
                    p = 1
                    for d in s:
                        p *= d
                    nparams += p
        total_params += nparams
        prev = ",".join(i.name for i, _ in node.inputs)[:40]
        print_row([f"{name} ({node.op})", str(out_shape), str(nparams),
                   prev], positions)
        print("_" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


_NODE_STYLE = {
    "Convolution": "fillcolor=\"#fb8072\"",
    "FullyConnected": "fillcolor=\"#fb8072\"",
    "BatchNorm": "fillcolor=\"#bebada\"",
    "Activation": "fillcolor=\"#ffffb3\"",
    "Pooling": "fillcolor=\"#80b1d3\"",
    "Concat": "fillcolor=\"#fdb462\"",
}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build DOT source for the symbol graph (reference
    ``mx.viz.plot_network`` returns a graphviz Digraph; here the DOT text
    itself — write it to a file and render with ``dot -Tpdf``)."""
    lines = [f'digraph "{title}" {{',
             '  node [shape=box, style=filled, fillcolor="#8dd3c7"];']
    hidden = set()
    if hide_weights:
        for node in _topo_nodes(symbol):
            for inp, _ in node.inputs:
                if inp.op == "null" and (
                        inp.name.endswith(("_weight", "_bias", "_gamma",
                                           "_beta", "_moving_mean",
                                           "_moving_var"))):
                    hidden.add(inp.name)
    for node in _topo_nodes(symbol):
        if node.name in hidden:
            continue
        style = _NODE_STYLE.get(node.op, "")
        label = node.name if node.op == "null" else \
            f"{node.name}\\n{node.op}"
        lines.append(f'  "{node.name}" [label="{label}"'
                     f'{", " + style if style else ""}];')
        for inp, _ in node.inputs:
            if inp.name in hidden:
                continue
            lines.append(f'  "{inp.name}" -> "{node.name}";')
    lines.append("}")
    return "\n".join(lines)
