"""The ``mx.sym`` namespace.

Reference: ``python/mxnet/symbol/__init__.py:?`` — op wrappers generated at
import time from the C++ registry (``symbol/register.py:?``).  Here every
op in the python registry gets a symbol-level builder that appends graph
nodes instead of executing.
"""
from __future__ import annotations

from ..ops import registry as _registry
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     zeros, ones, arange, _sym_op)
from . import contrib  # noqa: F401  (mx.sym.contrib namespace)

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones", "arange"]

# generate mx.sym.<op> for every registered op; ops land as module attrs so
# tab-completion and getattr both work (the reference generates these from
# the C++ registry at import)
for _opname in _registry.list_ops():
    if _opname in globals():
        continue
    globals()[_opname] = _sym_op(_opname)
    __all__.append(_opname)


def __getattr__(name):
    # ops registered after import (custom ops, plugins) resolve lazily
    if _registry.get_op(name) is not None:
        fn = _sym_op(name)
        globals()[name] = fn
        return fn
    raise AttributeError(f"module 'symbol' has no attribute {name!r}")
