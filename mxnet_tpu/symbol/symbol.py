"""The legacy symbolic API: lazy graph construction.

Reference: ``python/mxnet/symbol/symbol.py:?`` + the nnvm graph core
(``3rdparty/tvm/nnvm/``): a ``Symbol`` is a handle to a DAG of op nodes;
composition (`sym.FullyConnected(data, ...)`) appends nodes; ``bind`` /
``simple_bind`` compile the DAG into an ``Executor`` (SURVEY §3.3).

TPU-native redesign: nodes reference ops in the *python* op registry
(mxnet_tpu.ops) whose bodies are jnp/lax code, so an executor "bind" is
just a topological closure that XLA traces and fuses — nnvm's PlanMemory /
inplace passes are XLA's job now.  The JSON wire format is kept
byte-compatible with the reference's symbol-json (``nodes`` / ``arg_nodes``
/ ``heads``) so ``mx.sym.load`` reads real MXNet model files and
``tojson()`` round-trips through the SymbolBlock importer.
"""
from __future__ import annotations

import json

import numpy as np

from ..base import MXNetError
from ..name import NameManager
from ..ops import registry as _op_registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones"]


class _SymNode:
    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs")

    def __init__(self, op, name, attrs=None, inputs=None, num_outputs=1):
        self.op = op          # "null" for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs or [])   # [(node, oidx)]
        self.num_outputs = num_outputs

    def is_var(self):
        return self.op == "null"


# ops with >1 raw output: name -> (total_outputs, visible_outputs) given attrs
_MULTI_OUT = {
    "split": lambda a: (int(a.get("num_outputs", 1)),) * 2,
    "SliceChannel": lambda a: (int(a.get("num_outputs", 1)),) * 2,
    "topk": lambda a: (2, 2) if a.get("ret_typ") == "both" else (1, 1),
    "BatchNorm": lambda a: (3, 3 if a.get("output_mean_var") else 1),
    "batch_norm": lambda a: (3, 3 if a.get("output_mean_var") else 1),
    # quantization family: (out, min, max) triples
    **{k: (lambda a: (3, 3)) for k in (
        "quantize", "_contrib_quantize", "quantize_v2",
        "_contrib_quantize_v2", "requantize", "_contrib_requantize",
        "quantized_conv", "_contrib_quantized_conv",
        "quantized_fully_connected",
        "_contrib_quantized_fully_connected", "quantized_pooling",
        "_contrib_quantized_pooling", "quantized_flatten",
        "_contrib_quantized_flatten")},
    # detection multi-output contribs
    **{k: (lambda a: (3, 3)) for k in (
        "multibox_target", "MultiBoxTarget", "_contrib_MultiBoxTarget")},
    **{k: (lambda a: (2, 2)) for k in (
        "bipartite_matching", "_contrib_bipartite_matching")},
}

# parameter-bearing ops: ordered input names after ``data``; (name, is_aux,
# include(attrs)) — auto-created as Variables named ``{opname}_{input}``
# (reference: nnvm FListInputNames + gluon naming convention)
_ALWAYS = lambda a: True
_OP_INPUTS = {
    "FullyConnected": [("weight", False, _ALWAYS),
                       ("bias", False, lambda a: not a.get("no_bias", False))],
    "Convolution": [("weight", False, _ALWAYS),
                    ("bias", False, lambda a: not a.get("no_bias", False))],
    "Deconvolution": [("weight", False, _ALWAYS),
                      ("bias", False, lambda a: not a.get("no_bias", True))],
    "BatchNorm": [("gamma", False, _ALWAYS), ("beta", False, _ALWAYS),
                  ("moving_mean", True, _ALWAYS),
                  ("moving_var", True, _ALWAYS)],
    "LayerNorm": [("gamma", False, _ALWAYS), ("beta", False, _ALWAYS)],
    "InstanceNorm": [("gamma", False, _ALWAYS), ("beta", False, _ALWAYS)],
    "Embedding": [("weight", False, _ALWAYS)],
    "LeakyReLU": [("gamma", False, lambda a: a.get("act_type") == "prelu")],
    # output heads auto-create their label var (reference FListInputNames
    # includes 'label'; the var lands as e.g. 'softmax_label')
    "SoftmaxOutput": [("label", False, _ALWAYS)],
    "LinearRegressionOutput": [("label", False, _ALWAYS)],
    "LogisticRegressionOutput": [("label", False, _ALWAYS)],
    "MAERegressionOutput": [("label", False, _ALWAYS)],
}

_canon = {"fully_connected": "FullyConnected", "convolution": "Convolution",
          "deconvolution": "Deconvolution", "batch_norm": "BatchNorm",
          "layer_norm": "LayerNorm", "instance_norm": "InstanceNorm",
          "embedding": "Embedding", "leaky_relu": "LeakyReLU",
          "slice_channel": "SliceChannel",
          "softmax_output": "SoftmaxOutput",
          "linear_regression_output": "LinearRegressionOutput",
          "logistic_regression_output": "LogisticRegressionOutput",
          "mae_regression_output": "MAERegressionOutput"}


def _canon_op(op):
    return _canon.get(op, op)


class Symbol:
    """A handle to one or more outputs of a symbolic graph."""

    def __init__(self, heads):
        self._heads = list(heads)  # [(node, oidx)]

    # --- introspection ------------------------------------------------------

    @property
    def name(self):
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def _topo(self):
        """Topological (inputs-first) order of all reachable nodes."""
        order, seen = [], set()
        stack = [(n, False) for n, _ in reversed(self._heads)]
        while stack:
            node, done = stack.pop()
            if done:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for src, _ in reversed(node.inputs):
                if id(src) not in seen:
                    stack.append((src, False))
        return order

    def _vars(self):
        return [n for n in self._topo() if n.is_var()]

    def list_arguments(self):
        return [n.name for n in self._vars() if not n.attrs.get("__is_aux__")]

    def list_auxiliary_states(self):
        return [n.name for n in self._vars() if n.attrs.get("__is_aux__")]

    def list_inputs(self):
        return [n.name for n in self._vars()]

    def list_outputs(self):
        names = []
        for node, oidx in self._heads:
            if node.is_var():
                names.append(node.name)
            elif node.num_outputs == 1:
                names.append(node.name + "_output")
            else:
                names.append(f"{node.name}_output{oidx}")
        return names

    @property
    def num_outputs(self):
        return len(self._heads)

    def __len__(self):
        return len(self._heads)

    def __iter__(self):
        return (Symbol([h]) for h in self._heads)

    def __getitem__(self, index):
        if isinstance(index, str):
            for node in self._topo():
                total = node.num_outputs
                for oidx in range(total):
                    nm = node.name if node.is_var() else (
                        node.name + "_output" if total == 1
                        else f"{node.name}_output{oidx}")
                    if nm == index or node.name == index:
                        return Symbol([(node, oidx)])
            raise MXNetError(f"no output named {index!r}")
        if isinstance(index, slice):
            return Symbol(self._heads[index])
        return Symbol([self._heads[index]])

    def get_internals(self):
        heads = []
        for node in self._topo():
            for oidx in range(node.num_outputs if not node.is_var() else 1):
                heads.append((node, oidx))
        return Symbol(heads)

    def get_children(self):
        node = self._heads[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # --- attrs --------------------------------------------------------------

    def attr(self, key):
        v = self._heads[0][0].attrs.get(key)
        return None if v is None else str(v)

    def list_attr(self):
        return {k: str(v) for k, v in self._heads[0][0].attrs.items()}

    def attr_dict(self):
        return {n.name: {k: str(v) for k, v in n.attrs.items()}
                for n in self._topo() if n.attrs}

    def _set_attr(self, **kwargs):
        self._heads[0][0].attrs.update(kwargs)

    # --- arithmetic ---------------------------------------------------------

    def _binop(self, other, opname, scalar_opname, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _make_node(opname, [a, b], {})
        return _make_node(scalar_opname, [self], {"scalar": float(other)})

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", "_rdiv_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _make_node("_mul_scalar", [self], {"scalar": -1.0})

    def __eq__(self, o):  # MXNet symbols compare elementwise
        if isinstance(o, (Symbol, int, float)):
            return self._binop(o, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __hash__(self):
        return id(self)

    def __repr__(self):
        outs = ", ".join(self.list_outputs())
        return f"<Symbol {outs}>"

    def __getattr__(self, opname):
        # fluent op calls: x.reshape(...), x.sum(...) — resolve through the
        # registry (reference generates these methods too)
        if opname.startswith("_"):
            raise AttributeError(opname)
        if _op_registry.get_op(opname) is None:
            raise AttributeError(opname)

        def method(*args, **kwargs):
            from . import _sym_op
            return _sym_op(opname)(self, *args, **kwargs)

        method.__name__ = opname
        return method

    # --- serialization ------------------------------------------------------

    def tojson(self):
        order = self._topo()
        nid = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            entry = {"op": n.op, "name": n.name,
                     "inputs": [[nid[id(s)], oi, 0] for s, oi in n.inputs]}
            attrs = {k: str(v) for k, v in n.attrs.items()
                     if not k.startswith("__")}
            if n.is_var():
                aux_flags = {k: str(v) for k, v in n.attrs.items()
                             if k.startswith("__") and k != "__is_aux__"}
                attrs.update(aux_flags)
            if attrs:
                entry["attrs"] = attrs
            nodes.append(entry)
        graph = {
            "nodes": nodes,
            "arg_nodes": [i for i, n in enumerate(order) if n.is_var()],
            "node_row_ptr": list(range(len(order) + 1)),
            "heads": [[nid[id(n)], oi, 0] for n, oi in self._heads],
            "attrs": {"mxnet_version": ["int", 10700]},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # --- shape/type inference ----------------------------------------------

    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self._infer(args, kwargs)
        if arg_shapes and any(s is None for s in arg_shapes):
            missing = [n for n, s in zip(self.list_arguments(), arg_shapes)
                       if s is None]
            raise MXNetError(f"cannot infer shapes for arguments {missing}; "
                             "provide them to infer_shape")
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer(args, kwargs)

    def infer_type(self, *args, **kwargs):
        # dtype flows with shapes; default float32
        dtypes = {k: np.dtype(v) for k, v in kwargs.items()}
        arg_names = self.list_arguments()
        for pos, t in enumerate(args):
            if t is not None:
                dtypes[arg_names[pos]] = np.dtype(t)
        known, outs, auxs = self._infer((), {}, dtypes=dtypes, want="dtype")
        return known, outs, auxs

    def _infer(self, pos_shapes, kw_shapes, dtypes=None, want="shape"):
        import jax

        given = dict(kw_shapes)
        arg_names = self.list_arguments()
        for pos, s in enumerate(pos_shapes):
            if s is not None:
                given[arg_names[pos]] = s
        dtypes = dtypes or {}
        order = self._topo()
        # node id -> tuple of (shape, dtype) per output, or None if unknown
        info = {}
        for n in order:
            if n.is_var():
                # NB: `or` would treat a provided 0-d shape () as
                # missing (scalar constants from the ONNX importer)
                shape = given.get(n.name)
                if shape is None:
                    shape = n.attrs.get("__shape__")
                dt = dtypes.get(n.name) or np.dtype(
                    n.attrs.get("__dtype__", np.float32))
                info[id(n)] = None if shape is None else \
                    ((tuple(int(d) for d in shape), np.dtype(dt)),)
                continue
            # derive unknown param-shapes from the data input, then eval
            canon = _canon_op(n.op)
            if canon in _OP_INPUTS and n.inputs and \
                    info.get(id(n.inputs[0][0])) is not None:
                data_shape = info[id(n.inputs[0][0])][n.inputs[0][1]][0]
                rules = _param_shapes(canon, n.attrs, data_shape)
                for (src, _oi), pname in zip(
                        n.inputs[1:], [p for p, _, c in _OP_INPUTS[canon]
                                       if c(n.attrs)]):
                    if info.get(id(src)) is None and pname in rules:
                        dt = np.dtype(dtypes.get(src.name, np.float32))
                        info[id(src)] = ((tuple(rules[pname]), dt),)
            in_info = [info.get(id(s)) for s, _ in n.inputs]
            if any(i is None for i in in_info) or \
                    _op_registry.get_op(n.op) is None:
                info[id(n)] = None
                continue
            structs = [jax.ShapeDtypeStruct(*info[id(s)][oi])
                       for s, oi in n.inputs]
            try:
                outs = _eval_node(n.op, n.attrs, structs)
            except Exception:
                info[id(n)] = None
                continue
            info[id(n)] = tuple((tuple(o.shape), np.dtype(o.dtype))
                                for o in outs)

        def pick(entry, oidx=0):
            if entry is None:
                return None
            shape, dt = entry[oidx]
            return shape if want == "shape" else dt

        variables = self._vars()
        arg_i = [pick(info.get(id(n))) for n in variables
                 if not n.attrs.get("__is_aux__")]
        aux_i = [pick(info.get(id(n))) for n in variables
                 if n.attrs.get("__is_aux__")]
        out_i = [pick(info.get(id(n)), oi) for n, oi in self._heads]
        return arg_i, out_i, aux_i

    # --- binding ------------------------------------------------------------

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **kwargs):
        from ..executor import Executor

        return Executor._simple_bind(self, ctx, grad_req, type_dict, kwargs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from ..executor import Executor

        return Executor._bind(self, ctx, args, args_grad, grad_req,
                              aux_states)

    def eval(self, ctx=None, **kwargs):
        exe = self.bind(ctx, args=kwargs)
        return exe.forward()


# ---------------------------------------------------------------------------
# construction helpers
# ---------------------------------------------------------------------------

def _eval_node(op, attrs, structs):
    """Shape-only evaluation of one registry op (no compute)."""
    import jax

    from ..ndarray import NDArray

    fn = _op_registry.get_op(op)
    clean = {k: v for k, v in attrs.items() if not k.startswith("__")}

    def raw_fn(*raws):
        out = fn(*[NDArray(r) for r in raws], **clean)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o._data for o in outs)

    return jax.eval_shape(raw_fn, *structs)


def _param_shapes(op, attrs, data_shape):
    """Infer parameter shapes from the data shape (the role of the
    reference's per-op FInferShape backward-flow)."""
    try:
        if op == "FullyConnected":
            nh = int(attrs["num_hidden"])
            flat = attrs.get("flatten", True)
            in_dim = int(np.prod(data_shape[1:])) if flat else data_shape[-1]
            return {"weight": (nh, in_dim), "bias": (nh,)}
        if op == "Convolution":
            nf = int(attrs["num_filter"])
            kernel = tuple(attrs["kernel"])
            ng = int(attrs.get("num_group", 1))
            return {"weight": (nf, data_shape[1] // ng) + kernel,
                    "bias": (nf,)}
        if op == "Deconvolution":
            nf = int(attrs["num_filter"])
            kernel = tuple(attrs["kernel"])
            ng = int(attrs.get("num_group", 1))
            return {"weight": (data_shape[1], nf // ng) + kernel,
                    "bias": (nf,)}
        if op == "BatchNorm":
            ax = int(attrs.get("axis", 1)) % len(data_shape)
            c = (data_shape[ax],)
            return {"gamma": c, "beta": c, "moving_mean": c, "moving_var": c}
        if op in ("LayerNorm", "InstanceNorm"):
            ax = int(attrs.get("axis", -1)) % len(data_shape)
            c = (data_shape[ax],)
            return {"gamma": c, "beta": c}
        if op == "Embedding":
            return {"weight": (int(attrs["input_dim"]),
                               int(attrs["output_dim"]))}
        if op == "LeakyReLU":
            return {"gamma": (data_shape[1],)}
        if op == "SoftmaxOutput":
            multi = str(attrs.get("multi_output", False)).lower() in \
                ("true", "1")
            return {"label": (data_shape[0],) + data_shape[2:] if multi
                    else data_shape[:-1]}
        if op in ("LinearRegressionOutput", "LogisticRegressionOutput",
                  "MAERegressionOutput"):
            return {"label": data_shape}
    except (KeyError, IndexError):
        pass
    return {}


def _as_head(x):
    if isinstance(x, Symbol):
        if len(x._heads) != 1:
            raise MXNetError(
                f"symbol with {len(x._heads)} outputs used as a single "
                "input; select one with sym[i]")
        return x._heads[0]
    raise MXNetError(f"expected Symbol input, got {type(x).__name__}")


def _make_node(op, input_syms, attrs, name=None):
    canon = _canon_op(op)
    hint = canon.lower().lstrip("_")
    name = NameManager.current().get(name, hint)
    total, visible = 1, 1
    if canon in _MULTI_OUT:
        total, visible = _MULTI_OUT[canon](attrs)
    elif op in _MULTI_OUT:
        total, visible = _MULTI_OUT[op](attrs)
    node = _SymNode(op, name, attrs, [_as_head(s) for s in input_syms],
                    num_outputs=total)
    return Symbol([(node, i) for i in range(visible)])


def _sym_op(opname):
    """Build the symbol-level op function for a registry op."""

    def sym_op(*args, **kwargs):
        name = kwargs.pop("name", None)
        attrs = dict(kwargs.pop("attr", None) or {})
        sym_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                      if isinstance(kwargs[k], Symbol)}
        attrs.update(kwargs)
        inputs = []
        for a in args:
            if isinstance(a, Symbol):
                inputs.append(a)
            elif isinstance(a, (list, tuple)) and a and \
                    isinstance(a[0], Symbol):
                inputs.extend(a)  # Concat-style varargs list
        canon = _canon_op(opname)
        if canon in _OP_INPUTS:
            if not inputs and "data" in sym_kwargs:
                inputs.append(sym_kwargs.pop("data"))
            if not inputs:
                raise MXNetError(f"{opname} needs a data input")
            node_name = NameManager.current().get(name, canon.lower())
            name = node_name
            ordered = inputs[:1]       # data
            extra = list(inputs[1:])   # positionally-passed params
            for pname, is_aux, cond in _OP_INPUTS[canon]:
                if not cond(attrs):
                    continue
                if pname in sym_kwargs:
                    ordered.append(sym_kwargs.pop(pname))
                elif extra:
                    ordered.append(extra.pop(0))
                else:
                    v = Variable(f"{node_name}_{pname}")
                    if is_aux:
                        v._heads[0][0].attrs["__is_aux__"] = True
                    ordered.append(v)
            inputs = ordered + extra
        else:
            if not inputs and "data" in sym_kwargs:
                inputs.append(sym_kwargs.pop("data"))
            # non-param ops may still take named symbol inputs (e.g. lhs/rhs)
            inputs.extend(sym_kwargs.values())
        return _make_node(opname, inputs, attrs, name=name)

    sym_op.__name__ = opname
    return sym_op


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable (reference mx.sym.Variable)."""
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = np.dtype(dtype).name
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else \
            init.__class__.__name__
    attrs.update(kwargs)
    return Symbol([(_SymNode("null", name, attrs), 0)])


var = Variable


def Group(symbols):
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Parse reference nnvm symbol-json into a Symbol graph."""
    from ..gluon.symbol_block import _parse_attr

    graph = json.loads(json_str)
    nodes_js = graph["nodes"]
    arg_nodes = set(graph.get("arg_nodes", []))
    built = []
    for i, nj in enumerate(nodes_js):
        raw_attrs = nj.get("attrs") or nj.get("param") or {}
        attrs = {k: _parse_attr(v) for k, v in raw_attrs.items()}
        if nj["op"] == "null":
            node = _SymNode("null", nj["name"], attrs)
            # aux-state heuristic for reference files (they don't mark aux
            # in json; executors infer it from op mutable-input slots)
            if any(t in nj["name"] for t in ("moving_mean", "moving_var",
                                             "running_mean", "running_var")):
                node.attrs["__is_aux__"] = True
        else:
            canon = _canon_op(nj["op"])
            total, _vis = _MULTI_OUT[canon](attrs) if canon in _MULTI_OUT \
                else (1, 1)
            node = _SymNode(nj["op"], nj["name"], attrs, num_outputs=total)
        built.append(node)
    for nj, node in zip(nodes_js, built):
        node.inputs = [(built[e[0]], e[1]) for e in nj.get("inputs", [])]
    heads = [(built[h[0]], h[1]) for h in graph["heads"]]
    return Symbol(heads)


def zeros(shape, dtype=None, **kwargs):
    return _make_node("_zeros", [], {"shape": tuple(shape),
                                     "dtype": np.dtype(dtype or "float32").name},
                      name=kwargs.get("name"))


def ones(shape, dtype=None, **kwargs):
    return _make_node("_ones", [], {"shape": tuple(shape),
                                    "dtype": np.dtype(dtype or "float32").name},
                      name=kwargs.get("name"))


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, **kwargs):
    """Symbolic arange: zero-input creation node.  Defined explicitly
    (rather than via the generic op wrapper, which keeps only Symbol
    positionals) so positional start/stop work like the reference
    mx.sym.arange."""
    attrs = {"start": start, "step": step, "repeat": repeat}
    if stop is not None:
        attrs["stop"] = stop
    if dtype is not None:
        attrs["dtype"] = np.dtype(dtype).name
    return _make_node("arange", [], attrs, name=kwargs.get("name"))
