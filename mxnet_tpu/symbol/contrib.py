"""``mx.sym.contrib`` namespace.

Reference: ``python/mxnet/symbol/contrib.py:?``.  Symbol-level builders for
every contrib op: same lazy-graph treatment as the main ``mx.sym``
namespace (see ``symbol/__init__.py``).
"""
from __future__ import annotations

from ..ndarray import contrib as _nd_contrib
from ..ops import registry as _registry
from .symbol import _sym_op

__all__ = []
for _name in _nd_contrib.__all__:
    if _registry.get_op(_name) is not None:
        globals()[_name] = _sym_op(_name)
        __all__.append(_name)
del _name
