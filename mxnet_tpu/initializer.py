"""Weight initializers.

Reference: ``python/mxnet/initializer.py:?`` — an ``Initializer`` registry
(``@register``, ``create()``), pattern-dispatch on parameter names
(``InitDesc``), and the standard family: Zero/One/Constant/Uniform/Normal/
Orthogonal/Xavier/MSRAPrelu/Bilinear/LSTMBias/Mixed.

TPU-native: initializers produce values through jax PRNG sampling (keys from
mxnet_tpu.random) directly into device arrays; the name-pattern dispatch
(weight→init, bias→zero, gamma→one, ...) is preserved because the Gluon
Parameter machinery relies on it.
"""
from __future__ import annotations

import re

import numpy as np

from .base import MXNetError

_INIT_REGISTRY = {}


def register(klass):
    """Register an initializer under its lowercased class name
    (reference: ``mx.init.register``)."""
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(init, **kwargs):
    if init is None:
        return None
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        if name not in _INIT_REGISTRY:
            raise MXNetError(f"unknown initializer {init!r}; registered: "
                             f"{sorted(_INIT_REGISTRY)}")
        return _INIT_REGISTRY[name](**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


class InitDesc(str):
    """Parameter-name descriptor carrying init attrs
    (reference: python/mxnet/initializer.py:? ``InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer: dispatches on the parameter name suffix the same way
    the reference does (weight/bias/gamma/beta/mean/var and the special
    *_init attr override)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def set_verbosity(self, verbose=False, print_func=None):
        return self

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init_attr = desc.attrs.get("__init__", "")
        if init_attr:
            create(init_attr)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- family hooks --------------------------------------------------------
    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_bias(self, name, arr):
        self._set(arr, np.zeros(arr.shape, dtype=arr.dtype))

    def _init_gamma(self, name, arr):
        self._set(arr, np.ones(arr.shape, dtype=arr.dtype))

    def _init_beta(self, name, arr):
        self._set(arr, np.zeros(arr.shape, dtype=arr.dtype))

    def _init_zero(self, name, arr):
        self._set(arr, np.zeros(arr.shape, dtype=arr.dtype))

    def _init_one(self, name, arr):
        self._set(arr, np.ones(arr.shape, dtype=arr.dtype))

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    @staticmethod
    def _set(arr, value):
        import jax.numpy as jnp

        dt = arr.dtype
        arr._data = jnp.asarray(value).astype(dt)

    @staticmethod
    def _key():
        from . import random as mxrand

        return mxrand.next_key()

    def __repr__(self):
        kw = ", ".join(f"{k}={v}" for k, v in self._kwargs.items())
        return f"{type(self).__name__}({kw})"

    def dumps(self):
        import json

        return json.dumps([type(self).__name__.lower(), self._kwargs])


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._set(arr, np.zeros(arr.shape))


Zeros = Zero
_INIT_REGISTRY["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._set(arr, np.ones(arr.shape))


Ones = One
_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        self._set(arr, np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    """U(-scale, scale) — reference default scale 0.07."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        import jax

        arr._data = jax.random.uniform(
            self._key(), arr.shape, np.float32, minval=-self.scale,
            maxval=self.scale).astype(arr.dtype)


@register
class Normal(Initializer):
    """N(0, sigma) — reference default sigma 0.01."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        import jax

        arr._data = (self.sigma * jax.random.normal(
            self._key(), arr.shape, np.float32)).astype(arr.dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        import jax

        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(self._key(), (nout, nin), np.float32,
                                     minval=-1.0, maxval=1.0)
        else:
            tmp = jax.random.normal(self._key(), (nout, nin), np.float32)
        u, _, v = np.linalg.svd(np.asarray(tmp), full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        self._set(arr, self.scale * q.reshape(arr.shape))


@register
class Xavier(Initializer):
    """Glorot init (reference: ``mx.init.Xavier`` — gluon's default for
    weights is Uniform, model zoos use Xavier/MSRA explicitly)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        import jax

        shape = arr.shape
        if len(shape) < 2:
            hw_scale = 1.0
            fan_in = fan_out = float(shape[0]) if shape else 1.0
        else:
            hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
            fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type!r}")
        scale = np.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            raw = jax.random.uniform(self._key(), shape, np.float32,
                                     minval=-scale, maxval=scale)
        elif self.rnd_type == "gaussian":
            raw = scale * jax.random.normal(self._key(), shape, np.float32)
        else:
            raise MXNetError(f"bad rnd_type {self.rnd_type!r}")
        arr._data = raw.astype(arr.dtype)


@register
class MSRAPrelu(Xavier):
    """He init (reference: ``mx.init.MSRAPrelu``)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: deconv upsampling layers)."""

    def _init_weight(self, name, arr):
        weight = np.zeros(int(np.prod(arr.shape)), dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: ``mx.init.LSTMBias``)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, b)

    _init_bias = _init_weight


@register
class Mixed(Initializer):
    """Per-name-pattern initializer list (reference: ``mx.init.Mixed``)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers length mismatch")
        self.map = [(re.compile(p), init) for p, init in
                    zip(patterns, initializers)]

    def __call__(self, desc, arr):
        for prog, init in self.map:
            if prog.match(str(desc)):
                init(desc, arr)
                return
        raise MXNetError(
            f"parameter {desc} did not match any pattern; add '.*' as the "
            "last pattern")
