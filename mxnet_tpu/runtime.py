"""Runtime feature introspection.

Reference: ``python/mxnet/runtime.py:?`` + ``src/libinfo.cc:?`` —
``mx.runtime.Features()`` lists compile-time capabilities (CUDA, CUDNN,
MKLDNN, DIST_KVSTORE, INT64_TENSOR_SIZE, ...) with ``is_enabled(name)``
(SURVEY §2.1 row 10).

TPU-native: features reflect what this build actually provides — the jax/
XLA platforms present at runtime plus the framework's own subsystems
(native C++ runtime, recordio, pallas).  CUDA-family flags are present
and False so reference scripts probing them keep working.
"""
from __future__ import annotations

import collections


class Feature(collections.namedtuple("Feature", ["name", "enabled"])):
    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    feats = {}
    import jax

    platforms = set()
    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        pass
    feats["TPU"] = bool(platforms & {"tpu", "axon"})
    feats["CPU"] = True
    feats["XLA"] = True
    feats["JIT"] = True
    try:
        from jax.experimental import pallas  # noqa: F401

        feats["PALLAS"] = True
    except Exception:
        feats["PALLAS"] = False
    try:
        from . import _native

        feats["NATIVE_ENGINE"] = _native.available()
    except Exception:
        feats["NATIVE_ENGINE"] = False
    feats["RECORDIO"] = True
    feats["DIST_KVSTORE"] = True        # dist_tpu_sync over the mesh
    feats["SPARSE"] = True              # BCOO-backed row_sparse/csr
    feats["BF16"] = True
    feats["INT64_TENSOR_SIZE"] = True
    # reference flags that are hard-off in a TPU build
    for off in ("CUDA", "CUDNN", "NCCL", "TENSORRT", "MKLDNN", "OPENCV",
                "OPENMP", "F16C", "CAFFE", "PROFILER_NVTX"):
        feats[off] = False
    feats["SIGNAL_HANDLER"] = True
    feats["PROFILER"] = True
    return feats


class Features(collections.OrderedDict):
    """Reference ``mx.runtime.Features``: mapping name → Feature."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            cls.instance.update(
                {k: Feature(k, v) for k, v in _detect().items()})
        return cls.instance

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"feature '{feature_name}' is unknown; "
                               f"known: {sorted(self)}")
        return self[feature_name].enabled


def feature_list():
    """Reference ``mx.runtime.feature_list()``."""
    return list(Features().values())
