"""ctypes bindings for the native runtime (libmxtpu).

The C++ side (src/cpp/) carries the reference's native-runtime roles on
TPU hosts (SURVEY §2.1): the dependency engine (threaded_engine.cc analog)
schedules host-side work — record IO, decode, prefetch — with MXNet's
read-var/write-var conflict semantics; the pooled buffer allocator plays
pooled_storage_manager.h for host staging buffers; the indexed RecordIO
reader + batch prefetcher are iter_image_recordio_2.cc/iter_prefetcher.h.
Device-side scheduling belongs to XLA's async dispatch and needs no C++.

The library is built on demand with g++ (make -C src/cpp) and cached;
every consumer falls back to pure python when unavailable
(``native.available()`` gates the fast paths).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "lib", "Engine", "RecordReader", "Prefetcher",
           "pool_stats"]

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libmxtpu.so")
_SRC = os.path.normpath(os.path.join(_HERE, "..", "..", "src", "cpp"))


def _build():
    if not os.path.isdir(_SRC):
        return False
    try:
        subprocess.run(["make", "-C", _SRC], check=True,
                       capture_output=True, timeout=300)
        return os.path.isfile(_SO)
    except Exception:
        return False


def _bind(so):
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    so.MXTEngineCreate.restype = ctypes.c_void_p
    so.MXTEngineCreate.argtypes = [ctypes.c_int]
    so.MXTEngineDestroy.argtypes = [ctypes.c_void_p]
    so.MXTEngineNewVar.restype = ctypes.c_int64
    so.MXTEngineNewVar.argtypes = [ctypes.c_void_p]
    so.MXTEnginePush.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_void_p, i64p, ctypes.c_int,
                                 i64p, ctypes.c_int]
    so.MXTEngineWaitForVar.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    so.MXTEngineWaitAll.argtypes = [ctypes.c_void_p]
    so.MXTEngineVarVersion.restype = ctypes.c_uint64
    so.MXTEngineVarVersion.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    so.MXTGetLastError.restype = ctypes.c_char_p
    so.MXTRecordReaderCreate.restype = ctypes.c_void_p
    so.MXTRecordReaderCreate.argtypes = [ctypes.c_char_p]
    so.MXTRecordReaderDestroy.argtypes = [ctypes.c_void_p]
    so.MXTRecordReaderCount.restype = ctypes.c_int64
    so.MXTRecordReaderCount.argtypes = [ctypes.c_void_p]
    so.MXTRecordReaderSize.restype = ctypes.c_int64
    so.MXTRecordReaderSize.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    so.MXTRecordReaderOffset.restype = ctypes.c_int64
    so.MXTRecordReaderOffset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    so.MXTRecordReaderRead.restype = ctypes.c_int
    so.MXTRecordReaderRead.argtypes = [ctypes.c_void_p, ctypes.c_int64, u8p]
    so.MXTPrefetcherCreate.restype = ctypes.c_void_p
    so.MXTPrefetcherCreate.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_int]
    so.MXTPrefetcherDestroy.argtypes = [ctypes.c_void_p]
    so.MXTPrefetcherSchedule.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int]
    so.MXTPrefetcherNext.restype = ctypes.c_int
    so.MXTPrefetcherNext.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(u8p),
                                     ctypes.POINTER(i64p),
                                     i64p, i64p]
    so.MXTBatchFree.argtypes = [u8p, i64p, ctypes.c_int64, ctypes.c_int64]
    so.MXTPoolStats.argtypes = [i64p, i64p]
    return so


def lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("MXNET_TPU_NO_NATIVE"):
            return None
        if not os.path.isfile(_SO) and not _build():
            return None
        try:
            _LIB = _bind(ctypes.CDLL(_SO))
        except OSError:
            _LIB = None
        return _LIB


def available():
    return lib() is not None


def _i64arr(values):
    arr = (ctypes.c_int64 * len(values))(*values)
    return arr


_PUSH_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class Engine:
    """Dependency engine handle (reference Engine::PushAsync semantics,
    include/mxnet/engine.h:?).  Python callbacks re-acquire the GIL, so use
    this for IO-bound tasks or as the scheduler under native ops."""

    def __init__(self, nthreads=4):
        self._so = lib()
        if self._so is None:
            raise RuntimeError("native library unavailable")
        self._h = self._so.MXTEngineCreate(nthreads)
        self._cbs = []  # keep callbacks alive until shutdown

    def new_var(self):
        return self._so.MXTEngineNewVar(self._h)

    def push(self, fn, read_vars=(), write_vars=()):
        cb = _PUSH_CB(lambda _arg: fn())
        self._cbs.append(cb)
        self._so.MXTEnginePush(
            self._h, ctypes.cast(cb, ctypes.c_void_p), None,
            _i64arr(list(read_vars)), len(read_vars),
            _i64arr(list(write_vars)), len(write_vars))

    def wait_for_var(self, var):
        self._so.MXTEngineWaitForVar(self._h, var)

    def wait_all(self):
        self._so.MXTEngineWaitAll(self._h)
        self._cbs.clear()

    def var_version(self, var):
        return self._so.MXTEngineVarVersion(self._h, var)

    def __del__(self):
        if getattr(self, "_h", None):
            self._so.MXTEngineDestroy(self._h)
            self._h = None


class RecordReader:
    """Indexed native RecordIO reader (pread-based, thread-safe)."""

    def __init__(self, path):
        self._so = lib()
        if self._so is None:
            raise RuntimeError("native library unavailable")
        self._h = self._so.MXTRecordReaderCreate(path.encode())
        if not self._h:
            raise IOError(self._so.MXTGetLastError().decode())

    def __len__(self):
        return self._so.MXTRecordReaderCount(self._h)

    def offset(self, i):
        """Byte offset of record i's first part header (maps .idx file
        offsets onto scan-order indices)."""
        return self._so.MXTRecordReaderOffset(self._h, i)

    def read(self, i):
        size = self._so.MXTRecordReaderSize(self._h, i)
        if size < 0:
            raise IndexError(f"record index {i} out of range")
        buf = np.empty(size, dtype=np.uint8)
        rc = self._so.MXTRecordReaderRead(
            self._h, i, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if rc != 0:
            raise IOError("record read failed")
        return buf.tobytes()

    def close(self):
        if getattr(self, "_h", None):
            self._so.MXTRecordReaderDestroy(self._h)
            self._h = None

    def __del__(self):
        self.close()


class Prefetcher:
    """Batch prefetcher: schedule index lists, consume in schedule order.

    Wraps reader + engine; each batch returns a list of record payloads.
    Slots bound execution concurrency; the CALLER paces scheduling to
    bound buffered-batch memory (keep scheduled - consumed ~ capacity).
    """

    def __init__(self, path, nthreads=4, capacity=4):
        self._so = lib()
        if self._so is None:
            raise RuntimeError("native library unavailable")
        self._reader = RecordReader(path)
        self._engine = Engine(nthreads)
        self._h = self._so.MXTPrefetcherCreate(
            self._reader._h, self._engine._h, capacity)

    def __len__(self):
        return len(self._reader)

    def schedule(self, indices):
        idx = _i64arr([int(i) for i in indices])
        self._so.MXTPrefetcherSchedule(self._h, idx, len(indices))

    def next(self):
        """-> list[bytes] for the next scheduled batch; None when drained."""
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        data = u8p()
        offsets = i64p()
        n = ctypes.c_int64()
        nbytes = ctypes.c_int64()
        rc = self._so.MXTPrefetcherNext(
            self._h, ctypes.byref(data), ctypes.byref(offsets),
            ctypes.byref(n), ctypes.byref(nbytes))
        if rc == -1:
            return None
        if rc != 0:
            raise IOError(self._so.MXTGetLastError().decode())
        try:
            flat = np.ctypeslib.as_array(data, shape=(nbytes.value,)) \
                if nbytes.value else np.empty(0, np.uint8)
            offs = np.ctypeslib.as_array(offsets, shape=(n.value + 1,))
            return [flat[offs[j]:offs[j + 1]].tobytes()
                    for j in range(n.value)]
        finally:
            self._so.MXTBatchFree(data, offsets, n, nbytes)

    def close(self):
        if getattr(self, "_h", None):
            self._engine.wait_all()
            self._so.MXTPrefetcherDestroy(self._h)
            self._h = None
            self._reader.close()

    def __del__(self):
        self.close()


def pool_stats():
    """(hits, misses) of the native pooled buffer allocator."""
    so = lib()
    if so is None:
        return (0, 0)
    h = ctypes.c_int64()
    m = ctypes.c_int64()
    so.MXTPoolStats(ctypes.byref(h), ctypes.byref(m))
    return (h.value, m.value)
