"""Foundations: error types, dtype handling, naming utilities.

TPU-native re-design of the roles played in the reference by
``3rdparty/dmlc-core`` (logging / CHECK macros / parameter descriptors) and
``include/mxnet/base.h``.  There is no C ABI here (reference
``src/c_api/c_api.cc:?``): the framework is Python-first over jax, so errors
are ordinary Python exceptions rather than per-thread error strings fetched
via ``MXGetLastError``.
"""
from __future__ import annotations

import os
from typing import Any, Sequence

import numpy as np


class MXNetError(RuntimeError):
    """Framework error type (reference: ``dmlc::Error`` surfaced as
    ``mxnet.base.MXNetError`` via the C ABI, python/mxnet/base.py:?)."""


def check(cond: bool, msg: str = "") -> None:
    """CHECK-style assertion (reference ``dmlc/logging.h`` ``CHECK(x)``)."""
    if not cond:
        raise MXNetError(msg or "Check failed")


# --- dtype handling ---------------------------------------------------------
# The reference's mshadow type codes (mshadow/base.h:?): a stable int code per
# dtype crossing the C ABI.  We keep numpy dtypes as the canonical currency and
# accept strings / numpy types / jax dtypes everywhere.

_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": None,  # filled lazily from ml_dtypes via jnp
    "uint8": np.uint8,
    "int8": np.int8,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def resolve_dtype(dtype: Any):
    """Normalise a user-supplied dtype to a numpy dtype object."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import ml_dtypes  # ships with jax

            return np.dtype(ml_dtypes.bfloat16)
        if dtype not in _DTYPE_ALIASES:
            raise MXNetError(f"unknown dtype {dtype!r}")
        return np.dtype(_DTYPE_ALIASES[dtype])
    return np.dtype(dtype)


def dtype_name(dtype: Any) -> str:
    """Stable string name for a dtype (used in param serialization)."""
    return np.dtype(dtype).name


# --- shape utilities --------------------------------------------------------

def normalize_shape(shape) -> tuple:
    if shape is None:
        return None
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def getenv_int(name: str, default: int) -> int:
    """dmlc::GetEnv equivalent; the reference exposes ~100 MXNET_* env vars
    (docs/.../env_var.md:?).  We honour the same names where they map."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def getenv_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


_UUID_COUNTER = [0]


def gen_name(prefix: str) -> str:
    """Sequential unique names (reference: NameManager in python/mxnet/name.py:?)."""
    _UUID_COUNTER[0] += 1
    return f"{prefix}{_UUID_COUNTER[0]}"
