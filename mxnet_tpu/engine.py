"""Execution engine: engine-type levers + deferred imperative dispatch.

Reference: ``src/engine/`` — ``MXNET_ENGINE_TYPE`` selects
``ThreadedEnginePerDevice`` (default), ``ThreadedEngine`` or
``NaiveEngine`` (fully serial; THE lever for bisecting async/scheduling
bugs: errors surface at the faulting op with a usable stack), plus
``python/mxnet/engine.py`` bulk-execution hooks.

TPU analog: XLA's async dispatch plays the threaded engine's role, and
``jit`` plays bulking.  ``NaiveEngine`` here means

- ``hybridize()`` becomes a no-op (no CachedOp jit): every op runs
  imperatively, so a failure's python stack names the exact op/block;
- every op dispatch blocks until the result is ready
  (``jax.block_until_ready``), so device errors surface at the op that
  caused them instead of a later sync point;
- the Trainer's fused multi-tensor optimizer update falls back to
  per-parameter eager updates;
- op bulking (below) is bypassed entirely.

Select with ``MXT_ENGINE_TYPE=NaiveEngine`` (``MXNET_ENGINE_TYPE`` is
honoured too) or :func:`set_engine_type` at runtime.

Op bulking (deferred imperative dispatch)
-----------------------------------------

The reference engine's biggest imperative-mode lever is op bulking
(``MXNET_ENGINE_BULK_SIZE_*``, ``Imperative`` bulk scopes): consecutive
async ops are grouped into ONE scheduled unit so the per-op dispatch
cost is paid once per segment.  The TPU-native replica lives here:

* with bulking on (``MXT_ENGINE_BULK=1`` or ``with engine.bulk(n):``),
  ``apply_op`` does not execute — it appends the dispatch to a
  thread-local pending *segment* and hands back NDArrays whose raw
  value is a :class:`_PendingArray` placeholder (shape/dtype known via
  ``jax.eval_shape``, data not yet computed);
* the segment flushes as ONE ``jax.jit``-compiled callable.  Compiled
  segments live in an LRU cache keyed by the (op-name sequence,
  closure attrs, wiring, input shapes/dtypes) signature, so a
  steady-state training loop replays compiled segments with no
  retracing;
* flush triggers: the segment reaching the bulk size, a host sync
  (``asnumpy``/``wait_to_read``/``item``/``__getitem__`` on a pending
  array — any read of ``NDArray._data``), an ``autograd.record()``
  boundary, a CachedOp / FusedTrainStep / kvstore dispatch, and the
  explicit :func:`flush`;
* recording forces eager dispatch (tape semantics are untouched),
  NaiveEngine bypasses bulking, and the donation sanitizer's checks
  run at flush against the segment's real input buffers.

Off by default; the disabled cost in ``apply_op`` is one module-global
boolean test (telemetry-style).  See docs/engine.md for the full flush
contract.

Async tier (the ThreadedEngine analog)
--------------------------------------

The reference's L2 layer is the ThreadedEngine: the python thread never
executes ops, it only enqueues dependencies.  The TPU-native analog
lives on top of bulking (``MXNET_ENGINE_ASYNC``, on by default when
bulking is on):

* a **single background executor thread** takes finalized segments off
  a bounded queue and does cache lookup / ``jit`` compile / replay
  there, while the caller thread keeps appending ops to the *next*
  segment.  Worker exceptions are captured per-segment and re-raised at
  the caller's next materialization point (``NDArray._data``,
  ``flush()``, ``wait_to_read``) with the originating op names;
* **cross-flush stitching**: a segment whose inputs are still pending
  in the previously size-flushed segment records *stitch refs* instead
  of blocking — the worker resolves them (FIFO guarantees the producer
  ran first), so a 64-op chain replays as a handful of cached
  executables with zero host blocking between windows;
* **interned call-site keys**: steady-state dispatch skips per-op
  closure hashing and ``eval_shape`` entirely after first sight of a
  (call site, input-aval) pair, falling back to the full key when
  shapes/dtypes/attrs change;
* the same call-site interning backs a **record-path replay cache**:
  inside ``autograd.record()`` ops still dispatch eagerly (tape
  semantics untouched) but the per-op ``jax.vjp`` trace is replaced by
  cached jit-compiled forward/backward callables per call site.

``MXNET_ENGINE_ASYNC=0`` restores the exact synchronous bulking
behavior above.  ``flush()`` is a deterministic drain: on return, every
segment this thread submitted has executed and any captured worker
exception has been re-raised.
"""
from __future__ import annotations

import atexit
import contextlib
import os
import queue
import sys
import threading
import time
import types
import weakref
from collections import OrderedDict

import numpy as np

from .base import MXNetError
from . import sanitizer as _san
from . import telemetry
from .telemetry import costs as _costs
from .telemetry import memwatch as _mw
from .telemetry import retrace as _retrace

__all__ = ["engine_type", "set_engine_type", "is_naive", "bulk",
           "set_bulk_size", "bulk_size", "set_bulk_enabled", "bulk_enabled",
           "set_async_enabled", "async_enabled", "async_stats",
           "key_intern_stats", "shutdown_async",
           "flush", "pending_ops", "segment_cache_stats",
           "clear_segment_cache"]

_TYPES = ("ThreadedEnginePerDevice", "ThreadedEngine", "NaiveEngine")
_type = None
_naive = None  # cached (_type == "NaiveEngine"), spares a str compare per op


def engine_type():
    global _type, _naive
    if _type is None:
        env = os.environ.get(
            "MXT_ENGINE_TYPE",
            os.environ.get("MXNET_ENGINE_TYPE", _TYPES[0]))
        if env not in _TYPES:  # don't cache a bad value: raise EVERY call
            raise MXNetError(f"unknown engine type {env!r}; "
                             f"one of {_TYPES}")
        _type = env
        _naive = env == "NaiveEngine"
    return _type


def set_engine_type(name):
    """Runtime override (tests / debugging sessions)."""
    global _type, _naive
    if name not in _TYPES:
        raise MXNetError(f"unknown engine type {name!r}; one of {_TYPES}")
    _type = name
    _naive = name == "NaiveEngine"
    return name


def is_naive():
    return engine_type() == "NaiveEngine"


# --- reference python/mxnet/engine.py bulk hooks ----------------------------

def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_flag(name) -> bool:
    return os.environ.get(name, "").strip().lower() \
        not in ("", "0", "false", "off", "no")


#: reference defaults: MXNET_ENGINE_BULK_SIZE seeds the generic budget,
#: the _IN_TRAIN/_IN_INFER variants pick per-mode budgets (consulted via
#: autograd.is_training() at dispatch time)
_bulk_size = _env_int("MXNET_ENGINE_BULK_SIZE", 15)
_bulk_size_train = _env_int("MXNET_ENGINE_BULK_SIZE_IN_TRAIN", _bulk_size)
_bulk_size_infer = _env_int("MXNET_ENGINE_BULK_SIZE_IN_INFER", _bulk_size)

#: process-wide default for deferred dispatch (thread scopes override)
_bulk_default = _env_flag("MXT_ENGINE_BULK")
_bulk_scopes = 0  # number of live bulk() scopes across all threads

#: THE fast-path flag: apply_op's disabled path is one read of this
#: module global and a falsy branch — same contract as telemetry._enabled
_bulk_on = _bulk_default

#: async tier default: on unless MXNET_ENGINE_ASYNC=0 (it only matters
#: while bulking is enabled, which is itself opt-in)
_async_on = os.environ.get("MXNET_ENGINE_ASYNC", "1").strip().lower() \
    not in ("0", "false", "off", "no")

#: bounded worker queue: a caller that outruns the executor by this many
#: segments blocks on submit (backpressure) instead of growing unboundedly
_ASYNC_QUEUE_MAX = max(1, _env_int("MXNET_ENGINE_ASYNC_QUEUE", 8))


def _update_bulk_on():
    global _bulk_on
    _bulk_on = bool(_bulk_default or _bulk_scopes > 0)


def set_async_enabled(flag):
    """Runtime switch for the async executor tier (the env analog is
    ``MXNET_ENGINE_ASYNC``).  Returns the previous value.  Disabling
    drains this thread's in-flight segments first, so the switch is a
    deterministic boundary: ``set_async_enabled(False)`` restores the
    exact synchronous bulking behavior from the next op on."""
    global _async_on
    prev = _async_on
    if not flag:
        _drain_async()
    _async_on = bool(flag)
    return prev


def async_enabled():
    """Is the async executor tier enabled?"""
    return _async_on


def set_bulk_size(size):
    """Set how many deferred ops a pending segment may hold before it
    auto-flushes (the reference's ``MXNET_ENGINE_BULK_SIZE``).  Sets the
    generic budget and both the train/infer variants; returns the
    previous generic value.  A size ≤ 1 disables deferral even when
    bulking is enabled."""
    global _bulk_size, _bulk_size_train, _bulk_size_infer
    prev = _bulk_size
    _bulk_size = _bulk_size_train = _bulk_size_infer = int(size)
    return prev


def bulk_size():
    """The effective segment budget for the current mode."""
    return _effective_bulk_size()


def _effective_bulk_size():
    from . import autograd as ag

    return _bulk_size_train if ag.is_training() else _bulk_size_infer


def set_bulk_enabled(flag):
    """Process-wide default for deferred dispatch (the runtime analog of
    ``MXT_ENGINE_BULK=1``).  Returns the previous default.  Disabling
    flushes this thread's pending segment."""
    global _bulk_default
    prev = _bulk_default
    _bulk_default = bool(flag)
    _update_bulk_on()
    if not _bulk_default:
        flush("explicit")
    return prev


def bulk_enabled():
    """Is deferred dispatch enabled for the calling thread?"""
    e = _TLS.enabled
    return _bulk_default if e is None else e


@contextlib.contextmanager
def bulk(size):
    """``with engine.bulk(n):`` — enable deferred dispatch on this thread
    with segment budget ``n`` for the scope (the reference's
    ``Imperative`` bulk scope).  The pending segment flushes on exit, and
    the previous size/enable state is restored.  ``bulk(0)``/``bulk(1)``
    disables deferral in the scope."""
    global _bulk_scopes, _bulk_size, _bulk_size_train, _bulk_size_infer
    prev_sizes = (_bulk_size, _bulk_size_train, _bulk_size_infer)
    prev_enabled = _TLS.enabled
    set_bulk_size(size)
    _TLS.enabled = int(size) > 1
    _bulk_scopes += 1
    _update_bulk_on()
    try:
        yield
    finally:
        flush("explicit")
        _bulk_scopes -= 1
        _TLS.enabled = prev_enabled
        _bulk_size, _bulk_size_train, _bulk_size_infer = prev_sizes
        _update_bulk_on()


# --- deferred imperative dispatch -------------------------------------------

class _BulkTLS(threading.local):
    def __init__(self):
        self.enabled = None   # None → inherit the process default
        self.segment = None   # the thread's pending _Segment
        self.flushing = False
        self.last_async = None  # most recent async-submitted segment
        self.inflight = []      # async-submitted, not yet drained


_TLS = _BulkTLS()


class _PendingArray:
    """Placeholder raw value of an NDArray produced by a deferred op.

    Exposes the aval surface NDArray's cheap properties read
    (``shape``/``dtype``/``ndim``) without computing anything; any code
    path that needs the real buffer goes through ``NDArray._data``,
    which materializes via :func:`_materialize`."""

    __slots__ = ("_segment", "_slot", "shape", "dtype", "weak_type",
                 "__weakref__")

    def __init__(self, segment, slot, shape, dtype, weak_type):
        self._segment = segment
        self._slot = slot
        self.shape = shape
        self.dtype = dtype
        self.weak_type = weak_type
        # liveness registration: at flush, only slots whose placeholder
        # is still referenced are returned from the compiled segment —
        # dead intermediates are never materialized (XLA fuses them away)
        segment.phrefs.append(weakref.ref(self))

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):  # pragma: no cover - debug aid
        return (f"<pending {'x'.join(map(str, self.shape))} {self.dtype} "
                f"slot={self._slot}>")


class _SegOp:
    """One deferred dispatch: the pure fun, its input wiring and the
    output slot range it fills."""

    __slots__ = ("fun", "in_refs", "base", "n_out", "single", "name", "key",
                 "lift", "lifted")

    def __init__(self, fun, in_refs, base, n_out, single, name, key,
                 lift, lifted):
        self.fun = fun
        self.in_refs = in_refs   # tuple of ints: slot >= 0 | -(ext_idx+1)
        self.base = base
        self.n_out = n_out
        self.single = single
        self.name = name
        # replay-safety signature: (fun code+closure key, wiring, name)
        self.key = key
        self.lift = lift         # closure cell indices lifted to runtime args
        self.lifted = lifted     # their values at dispatch time


class _StitchRef:
    """A cross-flush external input: an output slot of an earlier
    async-submitted segment, resolved on the worker thread right before
    execution (FIFO queue order guarantees the producer segment ran
    first).  Stands in ``_Segment.ext`` where the raw will go.  Holds
    the producer's placeholder STRONGLY so its slot stays live (and
    therefore materialized) even if every NDArray referencing it has
    been rebound by the time the producer executes."""

    __slots__ = ("pending",)

    def __init__(self, pending):
        self.pending = pending

    @property
    def segment(self):
        return self.pending._segment

    @property
    def slot(self):
        return self.pending._slot


class _Segment:
    """The thread-local pending op segment (one engine bulk)."""

    __slots__ = ("ops", "ext", "ext_ids", "slots", "results", "error",
                 "error_delivered", "submitted", "stitched", "phrefs",
                 "_lock", "_done")

    def __init__(self):
        self.ops = []
        self.ext = []        # external input raws (or _StitchRefs), deduped
        self.ext_ids = {}    # id(raw) / stitch key -> index into ext
        self.slots = 0       # total output slots produced so far
        self.results = None  # list of raws per slot once executed
        self.error = None    # captured exception once a run failed
        self.error_delivered = False  # re-raised to the caller already?
        self.submitted = False        # handed to the async executor
        self.stitched = 0             # number of _StitchRef inputs
        self.phrefs = []     # weakrefs to issued placeholders (liveness)
        self._lock = threading.Lock()
        self._done = threading.Event()

    def execute(self, reason):
        """Run the segment (idempotent).  Raises on failure — the async
        worker catches and leaves the exception in ``self.error`` for
        re-raise at the caller's next materialization point."""
        with self._lock:
            if self.results is not None or self.error is not None:
                return
            try:
                self._execute_locked(reason)
            except BaseException as e:
                if self.error is None:
                    # failure outside the jfn-call window (key build,
                    # segment-fn construction): still capture it so an
                    # async caller sees the error at materialization
                    # instead of a silently result-less segment
                    names = ", ".join(op.name or "op" for op in self.ops[:8])
                    self._fail_locked(MXNetError(
                        f"bulked segment of {len(self.ops)} ops ({names}) "
                        f"failed at flush ({reason}): {e}"))
                raise
            finally:
                self._done.set()

    def _fail_locked(self, exc):
        self.error = exc
        self.ops = ()
        self.ext = ()
        self.ext_ids = None
        self.phrefs = ()
        return exc

    def _execute_locked(self, reason):
        n_ops = len(self.ops)
        telemetry.count("engine.bulk_flush")
        telemetry.count("engine.bulk_flush." + reason)
        telemetry.gauge("engine.bulk_segment_ops", n_ops)
        if self.stitched:
            # resolve cross-flush inputs: the producing segments were
            # submitted before this one, so on the worker they are done;
            # a caller-side (sync fallback) resolution may block briefly
            telemetry.count("engine.bulk_stitch")
            with _STATS_LOCK:
                _async_stats["stitched_segments"] += 1
            ext = self.ext
            for i, r in enumerate(ext):
                if r.__class__ is _StitchRef:
                    src = r.segment
                    src._done.wait()
                    if src.error is not None:
                        raise self._fail_locked(MXNetError(
                            f"bulked segment of {n_ops} ops consumed the "
                            f"output of an upstream stitched segment that "
                            f"failed: {src.error}")) from src.error
                    ext[i] = src.results[r.slot]
        if _san._enabled:
            # donation checks run at flush, against the segment's real
            # input buffers (pending intermediates have no buffer yet)
            for raw in self.ext:
                try:
                    _san.check(raw, "bulk segment input")
                except MXNetError as e:
                    raise self._fail_locked(e)
        # liveness pruning: only slots whose placeholder is still
        # referenced (directly by an NDArray, or strongly via a consumer
        # segment's _StitchRef) leave the compiled fn — dead
        # intermediates are fused away by XLA and never wrapped into
        # arrays, which is most of a replay's dispatch cost
        keep = set()
        for wr in self.phrefs:
            p = wr()
            if p is not None:
                keep.add(p._slot)
        keep = tuple(sorted(keep))
        key = (tuple(op.key for op in self.ops),
               tuple((tuple(r.shape), r.dtype,
                      bool(getattr(r, "weak_type", False)))
                     for r in self.ext),
               keep)
        entry = _cache_lookup(key)
        if entry is None:
            if _retrace._enabled and len(self.ops) > 1:
                # registered compile site, keyed per op sequence: a new
                # bulked segment program is fine, but a post-warmup
                # second signature for the SAME op sequence (diverging
                # external avals / liveness) is a retrace — e.g. an
                # unlifted float turning weak scalars back into baked
                # constants.  Single-op segments are the eager op
                # library: one compile per aval set is its design, and
                # interned call-site keys deliberately conflate contexts
                # (layers sharing an op), so they are not compile-once
                # sites
                _retrace.observe(
                    "engine_bulk", hash(key[0]),
                    {"ext": key[1], "keep": keep},
                    site="mxnet_tpu.engine:_Segment._execute_locked "
                         f"({len(self.ops)} ops)")
            entry = _CompiledSegment(
                _build_segment_fn(self.ops, self.slots, keep))
            _cache_insert(key, entry)
        first = not entry.executed
        scalars = tuple(_weak_scalar(v)
                        for op in self.ops for v in op.lifted)
        if _costs._enabled:
            # cost registry shares the segment-cache key, so a replayed
            # segment attributes its flops without re-analysis
            _costs.note("engine_bulk", key, entry.jfn,
                        (scalars,) + tuple(self.ext),
                        site="mxnet_tpu.engine:_Segment._execute_locked")
        prev_flushing = _TLS.flushing
        _TLS.flushing = True
        try:
            with telemetry.span("engine.bulk_compile" if first
                                else "engine.bulk_replay"):
                res = entry.jfn(scalars, *self.ext)
        except MXNetError as e:
            self._fail_locked(e)
            raise
        except Exception as e:
            names = ", ".join(op.name or "op" for op in self.ops[:8])
            if _mw._enabled:
                _mw.annotate_oom(e, context=f"bulk segment flush ({reason})")
            raise self._fail_locked(MXNetError(
                f"bulked segment of {n_ops} ops ({names}{', ...' if n_ops > 8 else ''}) "
                f"failed at flush ({reason}): {e}")) from e
        finally:
            _TLS.flushing = prev_flushing
        if first:
            entry.executed = True
            telemetry.count("engine.bulk_compile")
        results = [None] * self.slots
        for i, s in enumerate(keep):
            results[s] = res[i]
        self.results = results
        self.ops = ()
        self.ext = ()
        self.ext_ids = None
        self.phrefs = ()


class _CompiledSegment:
    __slots__ = ("jfn", "executed")

    def __init__(self, jfn):
        self.jfn = jfn
        self.executed = False


#: cache of lifted scalar attrs as committed jax scalars, keyed by
#: (type, value) so a python float (weak f32) never collides with a
#: np.float32/np.float64 (strong) — the aval, and therefore promotion
#: semantics, must match eager exactly
_SCALAR_CACHE = {}


def _weak_scalar(v):
    """A lifted float attr as a cached jax scalar: passing committed
    arrays into the compiled segment skips the per-replay python-float
    conversion (~2 us per scalar per call) while tracing to the same
    aval a raw python float would (jnp.asarray preserves weak typing),
    so eager-identical numerics are preserved."""
    key = (type(v), v)
    s = _SCALAR_CACHE.get(key)
    if s is None:
        if len(_SCALAR_CACHE) > 4096:
            _SCALAR_CACHE.clear()  # unbounded attr churn: drop and rebuild
        import jax.numpy as jnp

        s = _SCALAR_CACHE[key] = jnp.asarray(v)
    return s


# --- async executor (the ThreadedEngine analog) ------------------------------
# ONE background thread for the whole process: finalized segments are
# enqueued (bounded, FIFO) and the worker does cache lookup / compile /
# replay while caller threads keep appending ops.  FIFO is load-bearing:
# stitch refs rely on producer segments executing before consumers.

_async_stats = {"submitted": 0, "stitched_segments": 0,
                "stitched_inputs": 0, "max_queue_depth": 0,
                "wait_ms": 0.0}
#: guards _async_stats: caller threads bump counters while the async
#: worker bumps stitched_segments; keep this lock a LEAF (never acquire
#: another lock under it)
_STATS_LOCK = _san.wrap_lock(threading.Lock(), "engine._STATS_LOCK")


class _AsyncExecutor:
    def __init__(self, maxsize):
        self.q = queue.Queue(maxsize)
        self._thread = None
        self._lock = _san.wrap_lock(threading.Lock(),
                                    "engine._AsyncExecutor._lock")

    def ensure_thread(self):
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._worker, name="mxt-engine-async",
                    daemon=True)
                self._thread.start()

    def _worker(self):
        while True:
            item = self.q.get()
            if item is None:
                self.q.task_done()
                return
            seg, reason = item
            try:
                seg.execute(reason)
            except BaseException:
                # captured in seg.error; re-raised at the caller's next
                # materialization point (_data / flush / wait_to_read)
                pass
            finally:
                self.q.task_done()

    def stop(self, join=True):
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            self.q.put(None)
            if join:
                t.join(timeout=30)


_EXEC = _AsyncExecutor(_ASYNC_QUEUE_MAX)


# observers poked whenever a compute segment is dispatched to the async
# executor — the data plane's prefetcher uses this to count how often a
# host->device transfer was genuinely in flight DURING compute dispatch
# (overlap evidence, docs/data.md).  Callbacks must be cheap and never
# raise into the dispatch path.
_dispatch_callbacks = []


def register_dispatch_callback(cb):
    """Register ``cb(reason)`` to run after each async segment dispatch."""
    if cb not in _dispatch_callbacks:
        _dispatch_callbacks.append(cb)


def unregister_dispatch_callback(cb):
    try:
        _dispatch_callbacks.remove(cb)
    except ValueError:
        pass


def _submit_async(seg, reason):
    """Hand a finalized segment to the executor (blocking when the
    bounded queue is full — backpressure) and track it for drain."""
    seg.submitted = True
    _EXEC.ensure_thread()
    depth = _EXEC.q.qsize() + 1
    with _STATS_LOCK:
        _async_stats["submitted"] += 1
        if depth > _async_stats["max_queue_depth"]:
            _async_stats["max_queue_depth"] = depth
    if telemetry._enabled:
        telemetry.gauge("engine.async_queue_depth", depth)
    _EXEC.q.put((seg, reason))
    for cb in tuple(_dispatch_callbacks):
        try:
            cb(reason)
        except Exception:
            pass
    _TLS.last_async = seg
    inflight = _TLS.inflight
    if len(inflight) >= 4:
        # sweep: done-and-clean segments need no drain bookkeeping
        _TLS.inflight = inflight = [
            s for s in inflight
            if not s._done.is_set()
            or (s.error is not None and not s.error_delivered)]
    inflight.append(seg)


def _wait_done(seg):
    """Block until an async-submitted segment has executed, accounting
    the caller's stall as ``engine.bulk_async_wait_ms``."""
    if seg._done.is_set():
        return
    t0 = time.perf_counter()
    seg._done.wait()
    ms = (time.perf_counter() - t0) * 1e3
    with _STATS_LOCK:
        _async_stats["wait_ms"] += ms
    if telemetry._enabled:
        telemetry.count("engine.bulk_async_wait_ms", ms)


def _drain_async():
    """Deterministic drain: wait for every segment this thread submitted
    and re-raise the first captured worker exception not yet delivered."""
    inflight = _TLS.inflight
    if not inflight:
        return
    _TLS.inflight = []
    _TLS.last_async = None
    err = None
    for seg in inflight:
        _wait_done(seg)
        if seg.error is not None and not seg.error_delivered and err is None:
            seg.error_delivered = True
            err = seg.error
    if err is not None:
        raise err


def shutdown_async(join=True):
    """Drain this thread's in-flight segments and stop the executor
    thread (it restarts lazily on the next async submit).  Called at
    interpreter exit so no worker is mid-compile during teardown."""
    try:
        _drain_async()
    finally:
        _EXEC.stop(join=join)


atexit.register(shutdown_async)


def async_stats():
    """Counters for the async tier: segments submitted/stitched, the
    max observed queue depth and cumulative caller stall (ms)."""
    with _STATS_LOCK:
        return dict(_async_stats)


def _with_cells(fun, lift, values):
    """A copy of ``fun`` whose closure cells at indices ``lift`` hold
    ``values`` instead of their originals.  Fresh cells + FunctionType:
    the original closure (possibly shared across threads) is untouched."""
    cells = list(fun.__closure__)
    for i, v in zip(lift, values):
        cells[i] = types.CellType(v)
    g = types.FunctionType(fun.__code__, fun.__globals__, fun.__name__,
                           fun.__defaults__, tuple(cells))
    g.__kwdefaults__ = fun.__kwdefaults__
    return g


def _build_segment_fn(ops, n_slots, keep=None):
    """One jit-compiled callable replaying the whole segment: lifted
    scalar attrs + external raws in, the LIVE op-output slots (``keep``,
    all of them when None) out — dead intermediates stay inside the jit
    where XLA fuses them away instead of materializing buffers.

    Numerics contract: every op is bit-identical to its eager dispatch —
    float closure attrs are *runtime arguments* (``op.lift``), not trace
    constants, because eager per-primitive dispatch passes scalars as
    compiled-executable arguments while XLA rewrites e.g. division by an
    embedded constant into multiplication by its reciprocal (last ulp
    differs).  Value-independence also means a segment replays across
    attr changes (a decaying learning rate keeps its compiled segment).
    ACROSS ops inside one segment, XLA's backend may still contract a
    mul feeding an add into an fma (it ignores optimization_barrier when
    duplicating cheap producers into consumer fusions), so a multi-op
    chain can differ from eager in the last ulp — the same class of
    difference ``hybridize()`` exhibits; see docs/engine.md."""
    import jax

    ops = tuple(ops)

    def seg_fn(scalars, *ext):
        vals = [None] * n_slots
        pos = 0
        for op in ops:
            args = [vals[i] if i >= 0 else ext[-i - 1]
                    for i in op.in_refs]
            fun = op.fun
            # op.lift is static host metadata (the per-op lifted-cell
            # indices), fixed per segment signature — never a traced value.
            if op.lift:  # mxlint: disable=T2
                k = len(op.lift)
                fun = _with_cells(fun, op.lift, scalars[pos:pos + k])
                pos += k
            r = fun(*args)
            rt = (r,) if op.single else tuple(r)
            for j in range(op.n_out):
                vals[op.base + j] = rt[j]
        if keep is None:
            return tuple(vals)
        return tuple(vals[i] for i in keep)

    return jax.jit(seg_fn)


# --- segment cache (LRU) ----------------------------------------------------
# The async worker looks up / inserts while caller threads read stats or
# clear (tests, memory pressure): every access holds _SEG_LOCK — an
# OrderedDict move_to_end racing a clear() corrupts the dict otherwise.

_SEG_CACHE = OrderedDict()
_SEG_CACHE_MAX = max(1, _env_int("MXT_ENGINE_SEGMENT_CACHE", 256))
_SEG_LOCK = _san.wrap_lock(threading.Lock(), "engine._SEG_LOCK")
_seg_stats = {"hit": 0, "miss": 0}


def _cache_lookup(key):
    with _SEG_LOCK:
        entry = _SEG_CACHE.get(key)
        if entry is None:
            _seg_stats["miss"] += 1
        else:
            _SEG_CACHE.move_to_end(key)
            _seg_stats["hit"] += 1
    if entry is None:
        telemetry.count("engine.bulk_segment_cache_miss")
        return None
    telemetry.count("engine.bulk_segment_cache_hit")
    return entry


def _cache_insert(key, entry):
    with _SEG_LOCK:
        _SEG_CACHE[key] = entry
        while len(_SEG_CACHE) > _SEG_CACHE_MAX:
            _SEG_CACHE.popitem(last=False)


def segment_cache_stats():
    """{"hit": n, "miss": n, "size": n} for the compiled-segment cache.
    Safe against the async worker mutating the LRU concurrently."""
    with _SEG_LOCK:
        return dict(_seg_stats, size=len(_SEG_CACHE))


def clear_segment_cache():
    """Drop every compiled segment (tests / memory pressure).  Safe
    against the async worker mutating the LRU concurrently."""
    with _SEG_LOCK:
        _SEG_CACHE.clear()
        _seg_stats["hit"] = _seg_stats["miss"] = 0


# --- fun signature keying ---------------------------------------------------
# A deferred fun is usually a FRESH closure per call (``lambda a: jf(a, c)``
# built inside an op wrapper), so identity cannot key the cache.  The stable
# identity is the lambda's code object (a compile-time constant of its
# enclosing function) plus the VALUES in its closure cells — the analog of
# the reference keying bulked segments by op + dmlc::Parameter attrs.  Only
# conservatively-immutable closure values are admitted; anything else
# (device/numpy arrays, mutable objects) makes the op non-deferrable and it
# falls back to eager dispatch.

_IMMUTABLE_TYPES = (type(None), bool, int, float, complex, str, bytes,
                    np.dtype, np.generic, type)


class _Unkeyable(Exception):
    pass


def _key_component(v):
    if isinstance(v, _IMMUTABLE_TYPES):
        return v
    if isinstance(v, tuple):
        return tuple(_key_component(x) for x in v)
    if isinstance(v, frozenset):
        return frozenset(_key_component(x) for x in v)
    if isinstance(v, slice):
        # slices are unhashable before 3.12; canonicalize
        return ("__slice__", _key_component(v.start), _key_component(v.stop),
                _key_component(v.step))
    if callable(v):
        # functions/jnp ufuncs: behavior is fixed, identity is the key
        try:
            hash(v)
        except TypeError:
            raise _Unkeyable from None
        return v
    raise _Unkeyable


def _fun_key(fun):
    """``(key, lift)`` — a hashable signature of ``fun``'s computation
    plus the closure cell indices holding float attrs (lifted to runtime
    scalar arguments; their VALUES stay out of the key so a segment
    replays across attr changes).  None when the fun cannot be keyed
    soundly (array-valued closures, exotic callables)."""
    code = getattr(fun, "__code__", None)
    if code is None:
        try:
            hash(fun)
        except TypeError:
            return None
        return fun, ()  # C-level callable: identity IS the behavior
    lift = []
    try:
        cells = []
        for i, cell in enumerate(getattr(fun, "__closure__", None) or ()):
            try:
                v = cell.cell_contents
            except ValueError:
                return None
            if type(v) is float:
                lift.append(i)
                cells.append(("__scalar__", "weak_f"))
            elif isinstance(v, np.floating):
                lift.append(i)
                cells.append(("__scalar__", np.dtype(type(v)).str))
            else:
                cells.append(_key_component(v))
        defaults = tuple(_key_component(d)
                         for d in (getattr(fun, "__defaults__", None) or ()))
    except _Unkeyable:
        return None
    return (code, tuple(cells), defaults), tuple(lift)


# --- output-aval inference --------------------------------------------------
# eval_shape is paid once per (fun signature, input avals); steady-state
# deferral is a dict hit.

_AVAL_CACHE = {}
_AVAL_CACHE_MAX = 8192


def _out_avals(fun, fkey, lift, lifted, in_avals):
    """((shape, dtype, weak) per output, single) or None if the fun cannot
    be abstractly evaluated (concrete-value control flow — including a
    lifted float attr steering python branches, non-array outputs) —
    such ops dispatch eagerly."""
    import jax

    akey = (fkey, in_avals)
    if akey in _AVAL_CACHE:
        return _AVAL_CACHE[akey]
    try:
        structs = [jax.ShapeDtypeStruct(s, d, weak_type=w)
                   for s, d, w in in_avals]
        if lift:
            sc = tuple(
                jax.ShapeDtypeStruct((), np.float32, weak_type=True)
                if type(v) is float
                else jax.ShapeDtypeStruct((), np.dtype(type(v)))
                for v in lifted)
            out = jax.eval_shape(
                lambda s, *a: _with_cells(fun, lift, s)(*a), sc, *structs)
        else:
            out = jax.eval_shape(fun, *structs)
        single = not isinstance(out, (tuple, list))
        outs_t = (out,) if single else tuple(out)
        avals = tuple(
            (tuple(o.shape), np.dtype(o.dtype),
             bool(getattr(o, "weak_type", False)))
            for o in outs_t)
        res = (avals, single)
    except Exception:
        res = None
    if len(_AVAL_CACHE) >= _AVAL_CACHE_MAX:
        _AVAL_CACHE.clear()
    _AVAL_CACHE[akey] = res
    return res


# --- interned call-site keys -------------------------------------------------
# A dispatch site (the ``lambda a: jf(a, c)`` inside an op wrapper) is
# identified by its code object.  The FIRST dispatch through a site pays
# the full ``_fun_key`` closure hash + ``eval_shape``; the result is
# interned so steady-state dispatch is: dict hit on the code object, an
# identity sweep over the closure cells, and an aval compare — no tuple
# building, no hashing of nested keys, no ``_out_avals``.  Any change in
# closure attrs falls back to the full key; any new input aval signature
# adds a variant.  The same records back the record-path replay cache
# (``cached_vjp``).

class _Site:
    """Interned dispatch record for one call site (code object)."""

    __slots__ = ("cells", "defaults", "fkey", "lift", "variants",
                 "fwd", "bwd", "vjp_bad", "bwd_bad", "fast_i", "fast_v")


class _Variant:
    """One seen input-aval signature at a site, with its inferred
    output avals (None → signature is non-deferrable)."""

    __slots__ = ("in_sig", "avals", "single")

    def __init__(self, in_sig, avals, single):
        self.in_sig = in_sig
        self.avals = avals
        self.single = single


#: code object (or C callable) -> tuple of _Sites, MRU-first.  One code
#: object can serve several distinct closures (the `lambda a: jf(a, c)`
#: inside NDArray._binary is shared by add/mul/sub/div — jf differs),
#: so each distinct cells snapshot gets its own site, matched in order.
_SITE_CACHE = {}
_SITES_PER_CODE = 8

#: reviewed signature budget (mxlint T15): the segment cache compiles one
#: program per (op sequence, arg avals, platform) key, so steady state is
#: one signature per distinct hot call site — growth past that is the
#: retrace bug the runtime sanitizer (telemetry.retrace) flags
__compile_signatures__ = {
    "engine_bulk": "1 per segment key (op sequence x arg avals x platform)",
}
_intern_stats = {"hit": 0, "miss": 0}

#: types whose == is cheap and total — used for closure-cell revalidation
#: (top-level floats are lifted and only type-checked; a float here is a
#: cell of a NESTED function, value-compared exactly like ``_fun_key``
#: keys it; everything else must be identical or cheaply equal,
#: otherwise the site does not match)
_CHEAP_EQ = (int, float, str, bytes, tuple, np.dtype, slice, frozenset,
             complex)

#: cell-content types for which ``is`` and ``==`` coincide in practice —
#: used to pick a per-site discriminator cell so scanning the sites that
#: share one code object is an identity test, not a full cells sweep.
#: jax's ufunc type (what ``jnp.add`` is) is appended lazily by
#: ``_bind_hot_refs``.
_IDENTITY_STABLE = (types.FunctionType, types.BuiltinFunctionType,
                    type, types.ModuleType, np.ufunc)


def _cheap_same(v, s):
    if v is s:
        return True
    if type(v) is not type(s):
        return False
    if isinstance(v, _CHEAP_EQ):
        try:
            return bool(v == s)
        except Exception:
            return False
    if type(v) is types.FunctionType:
        # nested helper defined fresh on every call of the op wrapper
        # (e.g. ``matmul`` inside ``fully_connected``): structurally the
        # same function when code and closure agree — mirrors _fun_key
        if v.__code__ is not s.__code__:
            return False
        vc = v.__closure__ or ()
        sc = s.__closure__ or ()
        if len(vc) != len(sc):
            return False
        try:
            for a, b in zip(vc, sc):
                if not _cheap_same(a.cell_contents, b.cell_contents):
                    return False
        except ValueError:
            return False
        vd = v.__defaults__ or ()
        sd = s.__defaults__ or ()
        if len(vd) != len(sd):
            return False
        for a, b in zip(vd, sd):
            if not _cheap_same(a, b):
                return False
        return True
    return False


def _new_site(fun, fkey, lift):
    site = _Site()
    if fkey is None:
        # bail-fast site: this call site is unkeyable (e.g. an array in
        # the closure) — do NOT snapshot cells (could pin a big buffer),
        # every future dispatch through it short-circuits to eager
        site.cells = None
        site.defaults = ()
    else:
        cells = getattr(fun, "__closure__", None) or ()
        site.cells = tuple(c.cell_contents for c in cells)
        site.defaults = tuple(getattr(fun, "__defaults__", None) or ())
    site.fkey = fkey
    site.lift = lift
    # discriminator: the first non-lifted cell holding an identity-stable
    # value (for NDArray._binary's shared lambda that is the jnp function,
    # which is exactly what distinguishes add from mul from sub from div)
    site.fast_i = -1
    site.fast_v = None
    if site.cells:
        lifted_ix = set(lift)
        for i, v in enumerate(site.cells):
            if i not in lifted_ix and isinstance(v, _IDENTITY_STABLE):
                site.fast_i = i
                site.fast_v = v
                break
    site.variants = ()
    site.fwd = None
    site.bwd = None
    site.vjp_bad = fkey is None
    site.bwd_bad = False
    return site


def _cells_match(site, fun):
    scells = site.cells
    if scells is None:
        return True  # bail-fast site: cells are irrelevant
    cells = getattr(fun, "__closure__", None) or ()
    if len(cells) != len(scells):
        return False
    lift = site.lift
    li = 0
    nl = len(lift)
    for i, cell in enumerate(cells):
        try:
            v = cell.cell_contents
        except ValueError:
            return False
        s = scells[i]
        if li < nl and lift[li] == i:
            li += 1
            if type(v) is not type(s):
                return False
            continue
        if not _cheap_same(v, s):
            return False
    d = getattr(fun, "__defaults__", None) or ()
    sd = site.defaults
    if len(d) != len(sd):
        return False
    for v, s in zip(d, sd):
        if not _cheap_same(v, s):
            return False
    return True


def _lookup_site(fun):
    """(site, cache key) — the site whose closure-attr snapshot
    revalidates against this ``fun`` instance, or None.  A None key
    means the callable cannot be interned at all (unhashable)."""
    code = getattr(fun, "__code__", None)
    if code is None:
        try:
            sites = _SITE_CACHE.get(fun)
        except TypeError:
            return None, None
        return (sites[0] if sites else None), fun
    sites = _SITE_CACHE.get(code)
    if sites:
        cells = fun.__closure__
        for s in sites:
            fi = s.fast_i
            if fi >= 0:
                # discriminator first: identity-stable cell contents make
                # `is` exact here (a mismatch means _cells_match would
                # reject too), so non-matching sibling sites cost one
                # pointer compare instead of a full cells sweep.  A
                # python-function discriminator may be a fresh object per
                # call (nested helper) — only its code object is decisive.
                try:
                    v = cells[fi].cell_contents
                except (IndexError, TypeError, ValueError):
                    continue
                sv = s.fast_v
                if v is not sv:
                    if type(v) is not types.FunctionType \
                            or type(sv) is not types.FunctionType \
                            or v.__code__ is not sv.__code__:
                        continue
            if _cells_match(s, fun):
                return s, code
    return None, code


def _store_site(key, site):
    sites = _SITE_CACHE.get(key) or ()
    _SITE_CACHE[key] = (site,) + sites[:_SITES_PER_CODE - 1]
    return site


def _find_variant(site, nd_args):
    if len(nd_args) == 1:
        # unary fast path (scalar-binary lambdas land here): one aval
        # compare, no zip machinery
        raw = nd_args[0]._raw
        if raw.__class__ is _PendingArray:
            sh, dt, wk = raw.shape, raw.dtype, raw.weak_type
        else:
            try:
                sh = tuple(raw.shape)
                dt = raw.dtype
                wk = bool(getattr(raw, "weak_type", False))
            except Exception:
                return None
        for var in site.variants:
            sig = var.in_sig
            if len(sig) == 1:
                s = sig[0]
                # np.dtype instances for builtin types are singletons, so
                # `is` short-circuits the (slower) np.dtype.__eq__
                if s[0] == sh and (s[1] is dt or s[1] == dt) \
                        and s[2] == wk:
                    return var
        return None
    for var in site.variants:
        sig = var.in_sig
        if len(sig) != len(nd_args):
            continue
        ok = True
        for s, a in zip(sig, nd_args):
            raw = a._raw
            if raw.__class__ is _PendingArray:
                if raw.shape != s[0] or raw.dtype != s[1] \
                        or raw.weak_type != s[2]:
                    ok = False
                    break
            else:
                try:
                    if tuple(raw.shape) != s[0] or raw.dtype != s[1] or \
                            bool(getattr(raw, "weak_type", False)) != s[2]:
                        ok = False
                        break
                except Exception:
                    ok = False
                    break
        if ok:
            return var
    return None


def _add_variant(site, var):
    # newest-first, small cap; replaced wholesale (atomic under the GIL)
    site.variants = (var,) + site.variants[:3]


def key_intern_stats():
    """{"hit": n, "miss": n, "sites": n} for the interned call-site
    dispatch keys (the cheap replay path)."""
    return dict(_intern_stats,
                sites=sum(len(v) for v in _SITE_CACHE.values()))


# --- record-path replay cache ------------------------------------------------

def cached_vjp(fun, raws, name=""):
    """Cached jitted forward+vjp for an op dispatched under
    ``autograd.record()``.

    Recording keeps per-op eager dispatch (tape structure, Node wiring
    and flush semantics are untouched) but the per-call ``jax.vjp``
    TRACE — the single most expensive piece of an imperative training
    step — is replaced by two jit-compiled callables interned per call
    site: a forward replay and a recompute-vjp (forward residuals are
    recomputed in backward, the standard remat trade; float closure
    attrs are runtime args exactly like bulked segments).  Returns
    ``(outs, vjp)`` or None when the site cannot be cached soundly —
    the caller then falls back to plain ``jax.vjp``.

    Active only while bulking is on (``_bulk_on``) and the async tier is
    enabled; NaiveEngine, AMP scopes and an active per-op profiler
    bypass it like deferral itself.
    """
    if _jax is None:
        _bind_hot_refs()
    jax = _jax
    if _TLS.flushing or not bulk_enabled():
        return None
    if _effective_bulk_size() <= 1:
        return None
    if _naive if _naive is not None else is_naive():
        return None
    if _amp_mod._STATE["active"]:
        return None
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is not None and prof._state == "run":
        return None
    site, key = _lookup_site(fun)
    if site is None:
        if key is None:
            return None
        keyed = _fun_key(fun)
        try:
            site = _new_site(fun, *(keyed if keyed is not None
                                    else (None, ())))
        except ValueError:
            return None
        _store_site(key, site)
    if site.vjp_bad:
        return None
    for r in raws:
        if isinstance(r, jax.core.Tracer):
            return None
    if site.fwd is None:
        lift = site.lift
        if lift:
            def _fwd(scalars, *a, _f=fun, _l=lift):
                return _with_cells(_f, _l, scalars)(*a)

            def _bwd(scalars, cots, *a, _f=fun, _l=lift):
                return jax.vjp(_with_cells(_f, _l, scalars), *a)[1](cots)
        else:
            def _fwd(scalars, *a, _f=fun):
                return _f(*a)

            def _bwd(scalars, cots, *a, _f=fun):
                return jax.vjp(_f, *a)[1](cots)
        site.fwd = jax.jit(_fwd)
        site.bwd = jax.jit(_bwd)
    lifted = tuple(_weak_scalar(fun.__closure__[i].cell_contents)
                   for i in site.lift) if site.lift else ()
    try:
        outs = site.fwd(lifted, *raws)
    except Exception:
        # untraceable under jit (concrete-value control flow, non-array
        # outputs): permanently fall back to eager vjp at this site
        site.vjp_bad = True
        site.fwd = site.bwd = None
        return None

    def vjp(cots, _site=site, _lifted=lifted, _raws=raws, _fun=fun):
        if not _site.bwd_bad:
            try:
                return _site.bwd(_lifted, cots, *_raws)
            except Exception:
                _site.bwd_bad = True
        return jax.vjp(_fun, *_raws)[1](cots)

    return outs, vjp


# --- defer / flush / materialize --------------------------------------------

# hot-path module refs, bound once on first dispatch: maybe_defer runs
# per op, so per-call `from . import ...` statements are real overhead
_jax = None
_ag = None
_amp_mod = None


_Tracer = None


def _bind_hot_refs():
    global _jax, _ag, _amp_mod, _Tracer, _IDENTITY_STABLE
    import jax

    from . import amp, autograd

    _ag = autograd
    _amp_mod = amp
    _Tracer = jax.core.Tracer
    # jnp.add/subtract/... are jax ufunc singletons — module-level
    # identity-stable, ideal site discriminators for NDArray._binary
    ufunc_t = type(jax.numpy.add)
    if ufunc_t not in _IDENTITY_STABLE:
        _IDENTITY_STABLE = _IDENTITY_STABLE + (ufunc_t,)
    _jax = jax


def maybe_defer(fun, nd_args, name):
    """Append the dispatch to the pending segment instead of executing.

    Returns ``(single, raw_values)`` — raw values are `_PendingArray`
    placeholders (or real raws when the append triggered a size flush) —
    or None when the op must dispatch eagerly (recording, NaiveEngine,
    amp/profiler active, tracer operands, unkeyable closures...).
    Callers reach this only behind the ``_bulk_on`` fast-path flag.
    """
    if _jax is None:
        _bind_hot_refs()
    tls = _TLS
    if tls.flushing:
        return None
    e = tls.enabled
    if not (_bulk_default if e is None else e):
        return None
    ag_state = _ag._STATE
    size = _bulk_size_train if ag_state.training else _bulk_size_infer
    if size <= 1 or ag_state.recording:
        return None
    if _naive if _naive is not None else is_naive():
        return None
    if _amp_mod._STATE["active"]:
        return None
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is not None and prof._state == "run":
        return None  # per-op profiler events need real per-op timing

    # Cheap replay path: an interned site whose closure attrs revalidate
    # and whose input-aval signature has been seen skips _fun_key and
    # _out_avals entirely — steady-state dispatch is two dict hits.
    site, scode = _lookup_site(fun)
    var = None
    if site is not None:
        if site.fkey is None:
            return None  # known-unkeyable call site: bail fast
        var = _find_variant(site, nd_args)
    if var is not None:
        _intern_stats["hit"] += 1
        if var.avals is None:
            return None  # known non-deferrable signature
        fkey, lift = site.fkey, site.lift
        avals, single = var.avals, var.single
        key_head = site
        need_avals = False
    else:
        _intern_stats["miss"] += 1
        if site is not None:
            # cells revalidated: the closure key is still valid, only
            # this input-aval signature is new
            fkey, lift = site.fkey, site.lift
        else:
            keyed = _fun_key(fun)
            if keyed is None:
                if scode is not None:
                    try:
                        _store_site(scode, _new_site(fun, None, ()))
                    except ValueError:
                        pass
                return None
            fkey, lift = keyed
        avals = single = None
        key_head = None
        need_avals = True
    if lift:
        cl = fun.__closure__
        lifted = (cl[lift[0]].cell_contents,) if len(lift) == 1 \
            else tuple(cl[i].cell_contents for i in lift)
    else:
        lifted = ()

    seg = tls.segment
    if seg is None or seg.results is not None or seg.error is not None:
        seg = tls.segment = _Segment()
    in_refs = []
    in_avals = []
    new_ext = 0
    stitched = 0
    ext_ids = seg.ext_ids
    for a in nd_args:
        raw = a._raw
        if raw.__class__ is _PendingArray:
            src = raw._segment
            if src is seg:
                # same-segment ref: non-negative int = producer slot
                in_refs.append(raw._slot)
                if need_avals:
                    in_avals.append((raw.shape, raw.dtype, raw.weak_type))
                continue
            if src.results is not None:
                raw = src.results[raw._slot]  # already executed: resolve
                a._raw = raw
            elif src.error is None and src.submitted:
                # cross-flush stitch: reference the in-flight segment's
                # output slot instead of synchronizing on it here; the
                # worker resolves the ref once the producer has run
                skey = ("x", id(src), raw._slot)
                idx = ext_ids.get(skey)
                if idx is None:
                    idx = len(seg.ext)
                    seg.ext.append(_StitchRef(raw))
                    ext_ids[skey] = idx
                    new_ext += 1
                stitched += 1
                in_refs.append(-idx - 1)
                if need_avals:
                    in_avals.append((raw.shape, raw.dtype, raw.weak_type))
                continue
            else:
                raw = _materialize(raw)  # failed or sync-mode segment
                a._raw = raw
        if isinstance(raw, _Tracer):
            # inside someone else's trace (CachedOp deferred-init pass,
            # vjp re-trace): deferral would leak tracers out of the trace
            if new_ext:
                del seg.ext[-new_ext:]
                for r in list(ext_ids):
                    if ext_ids[r] >= len(seg.ext):
                        del ext_ids[r]
            return None
        idx = ext_ids.get(id(raw))
        if idx is None:
            idx = len(seg.ext)
            seg.ext.append(raw)
            ext_ids[id(raw)] = idx
            new_ext += 1
        # external ref: negative int = -(ext_idx + 1)
        in_refs.append(-idx - 1)
        if need_avals:
            in_avals.append((tuple(raw.shape), np.dtype(raw.dtype),
                             bool(getattr(raw, "weak_type", False))))
    if need_avals:
        in_sig = tuple(in_avals)
        info = _out_avals(fun, fkey, lift, lifted, in_sig)
        if site is None and scode is not None:
            try:
                site = _store_site(scode, _new_site(fun, fkey, lift))
            except ValueError:
                site = None
        if site is not None:
            _add_variant(site, _Variant(
                in_sig, None if info is None else info[0],
                None if info is None else info[1]))
            key_head = site
        if info is None:
            if new_ext:
                del seg.ext[-new_ext:]
                for r in list(seg.ext_ids):
                    if seg.ext_ids[r] >= len(seg.ext):
                        del seg.ext_ids[r]
            return None
        avals, single = info
    in_refs = tuple(in_refs)
    base = seg.slots
    n_out = len(avals)
    seg.slots = base + n_out
    # the interned _Site object doubles as the op's cache-key head:
    # hashing it is pointer identity instead of a deep closure-attr tuple
    ops = seg.ops
    ops.append(_SegOp(fun, in_refs, base, n_out, single, name,
                      (key_head if key_head is not None else fkey,
                       in_refs, name), lift, lifted))
    if stitched:
        seg.stitched += stitched
        with _STATS_LOCK:
            _async_stats["stitched_inputs"] += stitched
    # placeholders are created BEFORE the flush below so the liveness
    # scan in _execute_locked always sees this op's outputs as live
    if n_out == 1:
        sh, dt, wk = avals[0]
        outs = (_PendingArray(seg, base, sh, dt, wk),)
    else:
        outs = tuple(_PendingArray(seg, base + j, sh, dt, wk)
                     for j, (sh, dt, wk) in enumerate(avals))
    if len(ops) >= size:
        tls.segment = None
        if _async_on:
            _submit_async(seg, "size")
        else:
            seg.execute("size")
            return single, tuple(seg.results[o._slot] for o in outs)
    return single, outs


def flush(reason="explicit"):
    """Execute this thread's pending segment inline, then drain the async
    tier: wait for every segment this thread submitted to the worker and
    re-raise the first captured error, if any.  After ``flush()`` returns
    normally, every prior op has executed successfully — the synchronous
    barrier semantics of PR 4 are preserved.  Returns the number of ops
    flushed from the pending segment."""
    seg = _TLS.segment
    n = 0
    if seg is not None:
        _TLS.segment = None
        n = len(seg.ops)
        seg.execute(reason)
    _drain_async()
    return n


def pending_ops():
    """Ops sitting in this thread's pending segment (0 when idle)."""
    seg = _TLS.segment
    return len(seg.ops) if seg is not None else 0


def _materialize(pending, reason="host_sync"):
    """Resolve a `_PendingArray` to its computed raw buffer.

    Unsubmitted segments execute inline (counted as a ``reason`` flush);
    segments in flight on the async worker are waited on.  A captured
    worker exception is re-raised here, at the caller's materialization
    point, naming the originating op."""
    seg = pending._segment
    if seg.results is None:
        if seg.submitted:
            _wait_done(seg)
        elif seg.error is None:
            if seg is _TLS.segment:
                _TLS.segment = None
            seg.execute(reason)
    if seg.error is not None:
        seg.error_delivered = True
        raise seg.error
    return seg.results[pending._slot]
