"""Execution-engine debug levers.

Reference: ``src/engine/`` — ``MXNET_ENGINE_TYPE`` selects
``ThreadedEnginePerDevice`` (default), ``ThreadedEngine`` or
``NaiveEngine`` (fully serial; THE lever for bisecting async/scheduling
bugs: errors surface at the faulting op with a usable stack), plus
``python/mxnet/engine.py`` bulk-execution hooks.

TPU analog: XLA's async dispatch plays the threaded engine's role, and
``jit`` plays bulking.  ``NaiveEngine`` here means

- ``hybridize()`` becomes a no-op (no CachedOp jit): every op runs
  imperatively, so a failure's python stack names the exact op/block;
- every op dispatch blocks until the result is ready
  (``jax.block_until_ready``), so device errors surface at the op that
  caused them instead of a later sync point;
- the Trainer's fused multi-tensor optimizer update falls back to
  per-parameter eager updates.

Select with ``MXT_ENGINE_TYPE=NaiveEngine`` (``MXNET_ENGINE_TYPE`` is
honoured too) or :func:`set_engine_type` at runtime.
"""
from __future__ import annotations

import contextlib
import os

from .base import MXNetError

__all__ = ["engine_type", "set_engine_type", "is_naive", "bulk",
           "set_bulk_size"]

_TYPES = ("ThreadedEnginePerDevice", "ThreadedEngine", "NaiveEngine")
_type = None


def engine_type():
    global _type
    if _type is None:
        env = os.environ.get(
            "MXT_ENGINE_TYPE",
            os.environ.get("MXNET_ENGINE_TYPE", _TYPES[0]))
        if env not in _TYPES:  # don't cache a bad value: raise EVERY call
            raise MXNetError(f"unknown engine type {env!r}; "
                             f"one of {_TYPES}")
        _type = env
    return _type


def set_engine_type(name):
    """Runtime override (tests / debugging sessions)."""
    global _type
    if name not in _TYPES:
        raise MXNetError(f"unknown engine type {name!r}; one of {_TYPES}")
    _type = name
    return name


def is_naive():
    return engine_type() == "NaiveEngine"


# --- reference python/mxnet/engine.py bulk hooks ----------------------------

_bulk_size = 15  # reference default (MXNET_ENGINE_BULK_SIZE_*)


def set_bulk_size(size):
    """Reference tunes how many async ops the engine groups; XLA's jit IS
    the bulking mechanism here, so this records and returns the previous
    value for API compatibility."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
