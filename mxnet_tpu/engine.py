"""Execution engine: engine-type levers + deferred imperative dispatch.

Reference: ``src/engine/`` — ``MXNET_ENGINE_TYPE`` selects
``ThreadedEnginePerDevice`` (default), ``ThreadedEngine`` or
``NaiveEngine`` (fully serial; THE lever for bisecting async/scheduling
bugs: errors surface at the faulting op with a usable stack), plus
``python/mxnet/engine.py`` bulk-execution hooks.

TPU analog: XLA's async dispatch plays the threaded engine's role, and
``jit`` plays bulking.  ``NaiveEngine`` here means

- ``hybridize()`` becomes a no-op (no CachedOp jit): every op runs
  imperatively, so a failure's python stack names the exact op/block;
- every op dispatch blocks until the result is ready
  (``jax.block_until_ready``), so device errors surface at the op that
  caused them instead of a later sync point;
- the Trainer's fused multi-tensor optimizer update falls back to
  per-parameter eager updates;
- op bulking (below) is bypassed entirely.

Select with ``MXT_ENGINE_TYPE=NaiveEngine`` (``MXNET_ENGINE_TYPE`` is
honoured too) or :func:`set_engine_type` at runtime.

Op bulking (deferred imperative dispatch)
-----------------------------------------

The reference engine's biggest imperative-mode lever is op bulking
(``MXNET_ENGINE_BULK_SIZE_*``, ``Imperative`` bulk scopes): consecutive
async ops are grouped into ONE scheduled unit so the per-op dispatch
cost is paid once per segment.  The TPU-native replica lives here:

* with bulking on (``MXT_ENGINE_BULK=1`` or ``with engine.bulk(n):``),
  ``apply_op`` does not execute — it appends the dispatch to a
  thread-local pending *segment* and hands back NDArrays whose raw
  value is a :class:`_PendingArray` placeholder (shape/dtype known via
  ``jax.eval_shape``, data not yet computed);
* the segment flushes as ONE ``jax.jit``-compiled callable.  Compiled
  segments live in an LRU cache keyed by the (op-name sequence,
  closure attrs, wiring, input shapes/dtypes) signature, so a
  steady-state training loop replays compiled segments with no
  retracing;
* flush triggers: the segment reaching the bulk size, a host sync
  (``asnumpy``/``wait_to_read``/``item``/``__getitem__`` on a pending
  array — any read of ``NDArray._data``), an ``autograd.record()``
  boundary, a CachedOp / FusedTrainStep / kvstore dispatch, and the
  explicit :func:`flush`;
* recording forces eager dispatch (tape semantics are untouched),
  NaiveEngine bypasses bulking, and the donation sanitizer's checks
  run at flush against the segment's real input buffers.

Off by default; the disabled cost in ``apply_op`` is one module-global
boolean test (telemetry-style).  See docs/engine.md for the full flush
contract.
"""
from __future__ import annotations

import contextlib
import os
import threading
import types
from collections import OrderedDict

import numpy as np

from .base import MXNetError
from . import telemetry
from .telemetry import costs as _costs
from .telemetry import memwatch as _mw

__all__ = ["engine_type", "set_engine_type", "is_naive", "bulk",
           "set_bulk_size", "bulk_size", "set_bulk_enabled", "bulk_enabled",
           "flush", "pending_ops", "segment_cache_stats",
           "clear_segment_cache"]

_TYPES = ("ThreadedEnginePerDevice", "ThreadedEngine", "NaiveEngine")
_type = None


def engine_type():
    global _type
    if _type is None:
        env = os.environ.get(
            "MXT_ENGINE_TYPE",
            os.environ.get("MXNET_ENGINE_TYPE", _TYPES[0]))
        if env not in _TYPES:  # don't cache a bad value: raise EVERY call
            raise MXNetError(f"unknown engine type {env!r}; "
                             f"one of {_TYPES}")
        _type = env
    return _type


def set_engine_type(name):
    """Runtime override (tests / debugging sessions)."""
    global _type
    if name not in _TYPES:
        raise MXNetError(f"unknown engine type {name!r}; one of {_TYPES}")
    _type = name
    return name


def is_naive():
    return engine_type() == "NaiveEngine"


# --- reference python/mxnet/engine.py bulk hooks ----------------------------

def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_flag(name) -> bool:
    return os.environ.get(name, "").strip().lower() \
        not in ("", "0", "false", "off", "no")


#: reference defaults: MXNET_ENGINE_BULK_SIZE seeds the generic budget,
#: the _IN_TRAIN/_IN_INFER variants pick per-mode budgets (consulted via
#: autograd.is_training() at dispatch time)
_bulk_size = _env_int("MXNET_ENGINE_BULK_SIZE", 15)
_bulk_size_train = _env_int("MXNET_ENGINE_BULK_SIZE_IN_TRAIN", _bulk_size)
_bulk_size_infer = _env_int("MXNET_ENGINE_BULK_SIZE_IN_INFER", _bulk_size)

#: process-wide default for deferred dispatch (thread scopes override)
_bulk_default = _env_flag("MXT_ENGINE_BULK")
_bulk_scopes = 0  # number of live bulk() scopes across all threads

#: THE fast-path flag: apply_op's disabled path is one read of this
#: module global and a falsy branch — same contract as telemetry._enabled
_bulk_on = _bulk_default


def _update_bulk_on():
    global _bulk_on
    _bulk_on = bool(_bulk_default or _bulk_scopes > 0)


def set_bulk_size(size):
    """Set how many deferred ops a pending segment may hold before it
    auto-flushes (the reference's ``MXNET_ENGINE_BULK_SIZE``).  Sets the
    generic budget and both the train/infer variants; returns the
    previous generic value.  A size ≤ 1 disables deferral even when
    bulking is enabled."""
    global _bulk_size, _bulk_size_train, _bulk_size_infer
    prev = _bulk_size
    _bulk_size = _bulk_size_train = _bulk_size_infer = int(size)
    return prev


def bulk_size():
    """The effective segment budget for the current mode."""
    return _effective_bulk_size()


def _effective_bulk_size():
    from . import autograd as ag

    return _bulk_size_train if ag.is_training() else _bulk_size_infer


def set_bulk_enabled(flag):
    """Process-wide default for deferred dispatch (the runtime analog of
    ``MXT_ENGINE_BULK=1``).  Returns the previous default.  Disabling
    flushes this thread's pending segment."""
    global _bulk_default
    prev = _bulk_default
    _bulk_default = bool(flag)
    _update_bulk_on()
    if not _bulk_default:
        flush("explicit")
    return prev


def bulk_enabled():
    """Is deferred dispatch enabled for the calling thread?"""
    e = _TLS.enabled
    return _bulk_default if e is None else e


@contextlib.contextmanager
def bulk(size):
    """``with engine.bulk(n):`` — enable deferred dispatch on this thread
    with segment budget ``n`` for the scope (the reference's
    ``Imperative`` bulk scope).  The pending segment flushes on exit, and
    the previous size/enable state is restored.  ``bulk(0)``/``bulk(1)``
    disables deferral in the scope."""
    global _bulk_scopes, _bulk_size, _bulk_size_train, _bulk_size_infer
    prev_sizes = (_bulk_size, _bulk_size_train, _bulk_size_infer)
    prev_enabled = _TLS.enabled
    set_bulk_size(size)
    _TLS.enabled = int(size) > 1
    _bulk_scopes += 1
    _update_bulk_on()
    try:
        yield
    finally:
        flush("explicit")
        _bulk_scopes -= 1
        _TLS.enabled = prev_enabled
        _bulk_size, _bulk_size_train, _bulk_size_infer = prev_sizes
        _update_bulk_on()


# --- deferred imperative dispatch -------------------------------------------

class _BulkTLS(threading.local):
    def __init__(self):
        self.enabled = None   # None → inherit the process default
        self.segment = None   # the thread's pending _Segment
        self.flushing = False


_TLS = _BulkTLS()


class _PendingArray:
    """Placeholder raw value of an NDArray produced by a deferred op.

    Exposes the aval surface NDArray's cheap properties read
    (``shape``/``dtype``/``ndim``) without computing anything; any code
    path that needs the real buffer goes through ``NDArray._data``,
    which materializes via :func:`_materialize`."""

    __slots__ = ("_segment", "_slot", "shape", "dtype", "weak_type")

    def __init__(self, segment, slot, shape, dtype, weak_type):
        self._segment = segment
        self._slot = slot
        self.shape = shape
        self.dtype = dtype
        self.weak_type = weak_type

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):  # pragma: no cover - debug aid
        return (f"<pending {'x'.join(map(str, self.shape))} {self.dtype} "
                f"slot={self._slot}>")


class _SegOp:
    """One deferred dispatch: the pure fun, its input wiring and the
    output slot range it fills."""

    __slots__ = ("fun", "in_refs", "base", "n_out", "single", "name", "key",
                 "lift", "lifted")

    def __init__(self, fun, in_refs, base, n_out, single, name, key,
                 lift, lifted):
        self.fun = fun
        self.in_refs = in_refs   # tuple of ("e", ext_idx) | ("s", slot)
        self.base = base
        self.n_out = n_out
        self.single = single
        self.name = name
        # replay-safety signature: (fun code+closure key, wiring, name)
        self.key = key
        self.lift = lift         # closure cell indices lifted to runtime args
        self.lifted = lifted     # their values at dispatch time


class _Segment:
    """The thread-local pending op segment (one engine bulk)."""

    __slots__ = ("ops", "ext", "ext_ids", "slots", "results", "error",
                 "_lock")

    def __init__(self):
        self.ops = []
        self.ext = []        # external (materialized) input raws, deduped
        self.ext_ids = {}    # id(raw) -> index into ext
        self.slots = 0       # total output slots produced so far
        self.results = None  # list of raws per slot once executed
        self.error = None
        self._lock = threading.Lock()

    def execute(self, reason):
        with self._lock:
            if self.results is not None or self.error is not None:
                return
            self._execute_locked(reason)

    def _execute_locked(self, reason):
        from . import sanitizer as _san

        n_ops = len(self.ops)
        telemetry.count("engine.bulk_flush")
        telemetry.count("engine.bulk_flush." + reason)
        telemetry.gauge("engine.bulk_segment_ops", n_ops)
        if _san._enabled:
            # donation checks run at flush, against the segment's real
            # input buffers (pending intermediates have no buffer yet)
            for raw in self.ext:
                _san.check(raw, "bulk segment input")
        key = (tuple(op.key for op in self.ops),
               tuple((tuple(r.shape), str(np.dtype(r.dtype)),
                      bool(getattr(r, "weak_type", False)))
                     for r in self.ext))
        entry = _cache_lookup(key)
        if entry is None:
            entry = _CompiledSegment(_build_segment_fn(self.ops, self.slots))
            _cache_insert(key, entry)
        first = not entry.executed
        scalars = tuple(v for op in self.ops for v in op.lifted)
        if _costs._enabled:
            # cost registry shares the segment-cache key, so a replayed
            # segment attributes its flops without re-analysis
            _costs.note("engine_bulk", key, entry.jfn,
                        (scalars,) + tuple(self.ext))
        prev_flushing = _TLS.flushing
        _TLS.flushing = True
        try:
            with telemetry.span("engine.bulk_compile" if first
                                else "engine.bulk_replay"):
                res = entry.jfn(scalars, *self.ext)
        except MXNetError:
            self.error = True
            raise
        except Exception as e:
            self.error = True
            names = ", ".join(op.name or "op" for op in self.ops[:8])
            if _mw._enabled:
                _mw.annotate_oom(e, context=f"bulk segment flush ({reason})")
            raise MXNetError(
                f"bulked segment of {n_ops} ops ({names}{', ...' if n_ops > 8 else ''}) "
                f"failed at flush ({reason}): {e}") from e
        finally:
            _TLS.flushing = prev_flushing
            if self.error is not None:
                self.ops = ()
                self.ext = ()
                self.ext_ids = None
        if first:
            entry.executed = True
            telemetry.count("engine.bulk_compile")
        self.results = list(res)
        self.ops = ()
        self.ext = ()
        self.ext_ids = None


class _CompiledSegment:
    __slots__ = ("jfn", "executed")

    def __init__(self, jfn):
        self.jfn = jfn
        self.executed = False


def _with_cells(fun, lift, values):
    """A copy of ``fun`` whose closure cells at indices ``lift`` hold
    ``values`` instead of their originals.  Fresh cells + FunctionType:
    the original closure (possibly shared across threads) is untouched."""
    cells = list(fun.__closure__)
    for i, v in zip(lift, values):
        cells[i] = types.CellType(v)
    g = types.FunctionType(fun.__code__, fun.__globals__, fun.__name__,
                           fun.__defaults__, tuple(cells))
    g.__kwdefaults__ = fun.__kwdefaults__
    return g


def _build_segment_fn(ops, n_slots):
    """One jit-compiled callable replaying the whole segment: lifted
    scalar attrs + external raws in, every op-output slot out.

    Numerics contract: every op is bit-identical to its eager dispatch —
    float closure attrs are *runtime arguments* (``op.lift``), not trace
    constants, because eager per-primitive dispatch passes scalars as
    compiled-executable arguments while XLA rewrites e.g. division by an
    embedded constant into multiplication by its reciprocal (last ulp
    differs).  Value-independence also means a segment replays across
    attr changes (a decaying learning rate keeps its compiled segment).
    ACROSS ops inside one segment, XLA's backend may still contract a
    mul feeding an add into an fma (it ignores optimization_barrier when
    duplicating cheap producers into consumer fusions), so a multi-op
    chain can differ from eager in the last ulp — the same class of
    difference ``hybridize()`` exhibits; see docs/engine.md."""
    import jax

    ops = tuple(ops)

    def seg_fn(scalars, *ext):
        vals = [None] * n_slots
        pos = 0
        for op in ops:
            args = [ext[i] if kind == "e" else vals[i]
                    for kind, i in op.in_refs]
            fun = op.fun
            # op.lift is static host metadata (the per-op lifted-cell
            # indices), fixed per segment signature — never a traced value.
            if op.lift:  # mxlint: disable=T2
                k = len(op.lift)
                fun = _with_cells(fun, op.lift, scalars[pos:pos + k])
                pos += k
            r = fun(*args)
            rt = (r,) if op.single else tuple(r)
            for j in range(op.n_out):
                vals[op.base + j] = rt[j]
        return tuple(vals)

    return jax.jit(seg_fn)


# --- segment cache (LRU) ----------------------------------------------------

_SEG_CACHE = OrderedDict()
_SEG_CACHE_MAX = max(1, _env_int("MXT_ENGINE_SEGMENT_CACHE", 256))
_seg_stats = {"hit": 0, "miss": 0}


def _cache_lookup(key):
    entry = _SEG_CACHE.get(key)
    if entry is None:
        _seg_stats["miss"] += 1
        telemetry.count("engine.bulk_segment_cache_miss")
        return None
    _SEG_CACHE.move_to_end(key)
    _seg_stats["hit"] += 1
    telemetry.count("engine.bulk_segment_cache_hit")
    return entry


def _cache_insert(key, entry):
    _SEG_CACHE[key] = entry
    while len(_SEG_CACHE) > _SEG_CACHE_MAX:
        _SEG_CACHE.popitem(last=False)


def segment_cache_stats():
    """{"hit": n, "miss": n, "size": n} for the compiled-segment cache."""
    return dict(_seg_stats, size=len(_SEG_CACHE))


def clear_segment_cache():
    """Drop every compiled segment (tests / memory pressure)."""
    _SEG_CACHE.clear()
    _seg_stats["hit"] = _seg_stats["miss"] = 0


# --- fun signature keying ---------------------------------------------------
# A deferred fun is usually a FRESH closure per call (``lambda a: jf(a, c)``
# built inside an op wrapper), so identity cannot key the cache.  The stable
# identity is the lambda's code object (a compile-time constant of its
# enclosing function) plus the VALUES in its closure cells — the analog of
# the reference keying bulked segments by op + dmlc::Parameter attrs.  Only
# conservatively-immutable closure values are admitted; anything else
# (device/numpy arrays, mutable objects) makes the op non-deferrable and it
# falls back to eager dispatch.

_IMMUTABLE_TYPES = (type(None), bool, int, float, complex, str, bytes,
                    np.dtype, np.generic, type)


class _Unkeyable(Exception):
    pass


def _key_component(v):
    if isinstance(v, _IMMUTABLE_TYPES):
        return v
    if isinstance(v, tuple):
        return tuple(_key_component(x) for x in v)
    if isinstance(v, frozenset):
        return frozenset(_key_component(x) for x in v)
    if isinstance(v, slice):
        # slices are unhashable before 3.12; canonicalize
        return ("__slice__", _key_component(v.start), _key_component(v.stop),
                _key_component(v.step))
    if callable(v):
        # functions/jnp ufuncs: behavior is fixed, identity is the key
        try:
            hash(v)
        except TypeError:
            raise _Unkeyable from None
        return v
    raise _Unkeyable


def _fun_key(fun):
    """``(key, lift)`` — a hashable signature of ``fun``'s computation
    plus the closure cell indices holding float attrs (lifted to runtime
    scalar arguments; their VALUES stay out of the key so a segment
    replays across attr changes).  None when the fun cannot be keyed
    soundly (array-valued closures, exotic callables)."""
    code = getattr(fun, "__code__", None)
    if code is None:
        try:
            hash(fun)
        except TypeError:
            return None
        return fun, ()  # C-level callable: identity IS the behavior
    lift = []
    try:
        cells = []
        for i, cell in enumerate(getattr(fun, "__closure__", None) or ()):
            try:
                v = cell.cell_contents
            except ValueError:
                return None
            if type(v) is float:
                lift.append(i)
                cells.append(("__scalar__", "weak_f"))
            elif isinstance(v, np.floating):
                lift.append(i)
                cells.append(("__scalar__", np.dtype(type(v)).str))
            else:
                cells.append(_key_component(v))
        defaults = tuple(_key_component(d)
                         for d in (getattr(fun, "__defaults__", None) or ()))
    except _Unkeyable:
        return None
    return (code, tuple(cells), defaults), tuple(lift)


# --- output-aval inference --------------------------------------------------
# eval_shape is paid once per (fun signature, input avals); steady-state
# deferral is a dict hit.

_AVAL_CACHE = {}
_AVAL_CACHE_MAX = 8192


def _out_avals(fun, fkey, lift, lifted, in_avals):
    """((shape, dtype, weak) per output, single) or None if the fun cannot
    be abstractly evaluated (concrete-value control flow — including a
    lifted float attr steering python branches, non-array outputs) —
    such ops dispatch eagerly."""
    import jax

    akey = (fkey, in_avals)
    if akey in _AVAL_CACHE:
        return _AVAL_CACHE[akey]
    try:
        structs = [jax.ShapeDtypeStruct(s, d, weak_type=w)
                   for s, d, w in in_avals]
        if lift:
            sc = tuple(
                jax.ShapeDtypeStruct((), np.float32, weak_type=True)
                if type(v) is float
                else jax.ShapeDtypeStruct((), np.dtype(type(v)))
                for v in lifted)
            out = jax.eval_shape(
                lambda s, *a: _with_cells(fun, lift, s)(*a), sc, *structs)
        else:
            out = jax.eval_shape(fun, *structs)
        single = not isinstance(out, (tuple, list))
        outs_t = (out,) if single else tuple(out)
        avals = tuple(
            (tuple(o.shape), np.dtype(o.dtype),
             bool(getattr(o, "weak_type", False)))
            for o in outs_t)
        res = (avals, single)
    except Exception:
        res = None
    if len(_AVAL_CACHE) >= _AVAL_CACHE_MAX:
        _AVAL_CACHE.clear()
    _AVAL_CACHE[akey] = res
    return res


# --- defer / flush / materialize --------------------------------------------

def maybe_defer(fun, nd_args, name):
    """Append the dispatch to the pending segment instead of executing.

    Returns ``(single, raw_values)`` — raw values are `_PendingArray`
    placeholders (or real raws when the append triggered a size flush) —
    or None when the op must dispatch eagerly (recording, NaiveEngine,
    amp/profiler active, tracer operands, unkeyable closures...).
    Callers reach this only behind the ``_bulk_on`` fast-path flag.
    """
    import jax

    from . import autograd as ag

    if _TLS.flushing or not bulk_enabled():
        return None
    size = _effective_bulk_size()
    if size <= 1 or is_naive() or ag.is_recording():
        return None
    from . import amp as _amp

    if _amp.is_active():
        return None
    from .ops.registry import _profiler_mod

    if _profiler_mod() is not None:
        return None  # per-op profiler events need real per-op timing
    keyed = _fun_key(fun)
    if keyed is None:
        return None
    fkey, lift = keyed
    lifted = tuple(fun.__closure__[i].cell_contents for i in lift) \
        if lift else ()

    seg = _TLS.segment
    if seg is None or seg.results is not None or seg.error is not None:
        seg = _TLS.segment = _Segment()
    in_refs = []
    in_avals = []
    new_ext = 0
    for a in nd_args:
        raw = a._raw
        if raw.__class__ is _PendingArray:
            if raw._segment is seg:
                in_refs.append(("s", raw._slot))
                in_avals.append((raw.shape, raw.dtype, raw.weak_type))
                continue
            raw = _materialize(raw)  # older, already-executed segment
            a._raw = raw
        if isinstance(raw, jax.core.Tracer):
            # inside someone else's trace (CachedOp deferred-init pass,
            # vjp re-trace): deferral would leak tracers out of the trace
            if new_ext:
                del seg.ext[-new_ext:]
                for r in list(seg.ext_ids):
                    if seg.ext_ids[r] >= len(seg.ext):
                        del seg.ext_ids[r]
            return None
        idx = seg.ext_ids.get(id(raw))
        if idx is None:
            idx = len(seg.ext)
            seg.ext.append(raw)
            seg.ext_ids[id(raw)] = idx
            new_ext += 1
        in_refs.append(("e", idx))
        in_avals.append((tuple(raw.shape), np.dtype(raw.dtype),
                         bool(getattr(raw, "weak_type", False))))
    info = _out_avals(fun, fkey, lift, lifted, tuple(in_avals))
    if info is None:
        if new_ext:
            del seg.ext[-new_ext:]
            for r in list(seg.ext_ids):
                if seg.ext_ids[r] >= len(seg.ext):
                    del seg.ext_ids[r]
        return None
    avals, single = info
    in_refs = tuple(in_refs)
    base = seg.slots
    seg.slots += len(avals)
    seg.ops.append(_SegOp(fun, in_refs, base, len(avals), single, name,
                          (fkey, in_refs, name), lift, lifted))
    if len(seg.ops) >= size:
        _TLS.segment = None
        seg.execute("size")
        return single, tuple(seg.results[base + j]
                             for j in range(len(avals)))
    return single, tuple(
        _PendingArray(seg, base + j, sh, dt, wk)
        for j, (sh, dt, wk) in enumerate(avals))


def flush(reason="explicit"):
    """Execute this thread's pending segment (no-op when empty).  Every
    NDArray holding a pending placeholder resolves to its computed buffer
    on next access.  Returns the number of ops flushed."""
    seg = _TLS.segment
    if seg is None:
        return 0
    _TLS.segment = None
    n = len(seg.ops)
    seg.execute(reason)
    return n


def pending_ops():
    """Ops sitting in this thread's pending segment (0 when idle)."""
    seg = _TLS.segment
    return len(seg.ops) if seg is not None else 0


def _materialize(pending, reason="host_sync"):
    """Resolve a `_PendingArray` to its computed raw buffer, executing its
    segment if that has not happened yet (counted as a ``reason`` flush)."""
    seg = pending._segment
    if seg.results is None:
        if seg is _TLS.segment:
            _TLS.segment = None
        seg.execute(reason)
    if seg.error is not None:
        raise MXNetError(
            "reading an NDArray whose bulked segment failed to execute; "
            "see the original flush error above")
    return seg.results[pending._slot]
