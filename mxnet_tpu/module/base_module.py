"""BaseModule: the fit/score/predict training-loop contract.

Reference: ``python/mxnet/module/base_module.py:?`` — ``fit()`` drives
forward_backward/update/update_metric over a DataIter, with initializer,
optimizer, kvstore, checkpoint and Speedometer hooks (SURVEY §3.3).

TPU-native: the loop is unchanged (it's python); the per-batch work lands
in one XLA program per bucket/shape instead of the reference's
executor-group per-op engine pushes.
"""
from __future__ import annotations

import logging
import time

from .. import metric as _metric
from ..base import MXNetError

_logger = logging.getLogger(__name__)


class BaseModule:
    """Abstract interface; Module and BucketingModule implement it."""

    def __init__(self, logger=None):
        self.logger = logger or _logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.for_training = False
        self.inputs_need_grad = False

    # --- abstract surface ---------------------------------------------------

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    # --- shared loop machinery ----------------------------------------------

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        if not (self.binded and self.params_initialized):
            raise MXNetError("module must be bound and initialized")
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch, nbatch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        from .. import nd

        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            pad = getattr(batch, "pad", 0) or 0
            if pad:
                outs = [o[:o.shape[0] - pad] for o in outs]
            outputs.append(outs)
        if not outputs:
            return []
        if merge_batches:
            n_out = len(outputs[0])
            merged = [nd.concat(*[b[i] for b in outputs], dim=0)
                      for i in range(n_out)]
            return merged[0] if n_out == 1 else merged
        return outputs

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The reference's one-call training loop (base_module.py:? fit)."""
        if num_epoch is None:
            raise MXNetError("num_epoch is required for fit")
        from .. import initializer as _init

        initializer = initializer or _init.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    def install_monitor(self, monitor):
        pass

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    @property
    def symbol(self):
        return getattr(self, "_symbol", None)


class _BatchEndParam:
    __slots__ = ("epoch", "nbatch", "eval_metric", "locals")

    def __init__(self, epoch, nbatch, eval_metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = None


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
