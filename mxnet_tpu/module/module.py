"""Module: a Symbol bound to data shapes + optimizer.

Reference: ``python/mxnet/module/module.py:?`` +
``executor_group.py DataParallelExecutorGroup:?``.  The reference slices
each batch across a ctx list and keeps one GraphExecutor per device;
gradients meet in the kvstore.

TPU-native redesign: ONE executor — data parallelism is the mesh's job
(GSPMD shards the same XLA program across devices; mxnet_tpu.parallel), so
the per-device executor group collapses.  A ctx list is accepted for API
compatibility and handled by sharding the batch over the mesh data axis
when one is active.
"""
from __future__ import annotations

import numpy as np

from .. import initializer as _init
from .. import optimizer as _opt
from ..base import MXNetError
from ..context import current_context
from ..initializer import InitDesc
from ..ndarray import NDArray
from .base_module import BaseModule


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        if isinstance(context, (list, tuple)):
            context = context[0] if context else None
        self._context = context or current_context()
        self._fixed_param_names = set(fixed_param_names or [])
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._data_shapes = None
        self._label_shapes = None
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()

    # --- bind ---------------------------------------------------------------

    @staticmethod
    def _shape_dict(shapes):
        out = {}
        for item in shapes or []:
            if hasattr(item, "name"):
                out[item.name] = tuple(item.shape)
            else:
                name, shape = item[0], item[1]
                out[name] = tuple(shape)
        return out

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._data_shapes = self._shape_dict(data_shapes)
        self._label_shapes = self._shape_dict(label_shapes)
        shapes = dict(self._data_shapes)
        shapes.update(self._label_shapes)
        reqs = {}
        for n in self._symbol.list_arguments():
            if not for_training:
                reqs[n] = "null"
            elif n in self._fixed_param_names:
                reqs[n] = "null"
            elif n in self._data_names:
                reqs[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names:
                reqs[n] = "null"
            else:
                reqs[n] = grad_req
        old_exec = self._exec if shared_module is None else \
            shared_module._exec
        self._exec = self._symbol.simple_bind(
            ctx=self._context, grad_req=reqs, **shapes)
        if old_exec is not None and self.params_initialized:
            self._exec.copy_params_from(
                old_exec.arg_dict, old_exec.aux_dict)
        if shared_module is not None and shared_module.params_initialized:
            self.params_initialized = True
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    # --- params -------------------------------------------------------------

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        initializer = initializer or _init.Uniform(0.01)
        attr_dict = self._symbol.attr_dict()
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                self._set_array(arr, arg_params[name])
            elif arg_params and not allow_missing and name not in arg_params:
                raise MXNetError(f"arg_params missing {name!r}")
            else:
                desc = InitDesc(name, attr_dict.get(name, {}))
                initializer(desc, arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params and name in aux_params:
                self._set_array(arr, aux_params[name])
            else:
                desc = InitDesc(name, attr_dict.get(name, {}))
                initializer(desc, arr)
        self.params_initialized = True

    @staticmethod
    def _set_array(dst, src):
        raw = src._data if isinstance(src, NDArray) else NDArray(src)._data
        dst._data = raw.astype(dst.dtype) if \
            np.dtype(raw.dtype) != np.dtype(dst.dtype) else raw

    def get_params(self):
        if not self.binded:
            raise MXNetError("module not bound")
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # --- optimizer ----------------------------------------------------------

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, _opt.Optimizer):
            self._optimizer = optimizer
        else:
            opt_params = dict(optimizer_params)
            idx2name = dict(enumerate(self._param_names))
            opt_params.setdefault("param_idx2name", idx2name)
            # the reference normalizes by batch size here
            # (module/module.py:? init_optimizer rescale_grad default)
            if self._data_shapes:
                batch = next(iter(self._data_shapes.values()))[0]
                opt_params.setdefault("rescale_grad", 1.0 / batch)
            self._optimizer = _opt.create(optimizer, **opt_params)
        self._updater = _opt.get_updater(self._optimizer)
        from .. import kvstore as _kv

        self._kvstore = None
        if kvstore:
            kv = kvstore if not isinstance(kvstore, str) else \
                _kv.create(kvstore)
            # single-process local kvstore adds nothing over direct update;
            # keep it for dist modes where push/pull crosses the mesh
            if getattr(kv, "num_workers", 1) > 1 or \
                    not isinstance(kvstore, str) or \
                    "dist" in getattr(kv, "type", str(kvstore)):
                self._kvstore = kv
                for i, name in enumerate(self._param_names):
                    self._kvstore.init(i, self._exec.arg_dict[name])
        self.optimizer_initialized = True

    # --- compute ------------------------------------------------------------

    def forward(self, data_batch, is_train=None):
        if not self.binded:
            raise MXNetError("module not bound")
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        data = data_batch.data if hasattr(data_batch, "data") else data_batch
        for name, arr in zip(self._data_names, data):
            feeds[name] = arr
        labels = getattr(data_batch, "label", None) or []
        for name, arr in zip(self._label_names, labels):
            if name in self._exec.arg_dict:
                feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("call init_optimizer before update")
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            weight = self._exec.arg_dict[name]
            if self._kvstore is not None:
                self._kvstore.push(i, grad)
                self._kvstore.pull(i, out=grad)
            self._updater(i, grad, weight)

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # --- checkpoint ---------------------------------------------------------

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from .. import serialization

        arg, aux = self.get_params()
        serialization.save_checkpoint(prefix, epoch, symbol=self._symbol,
                                      arg_params=arg, aux_params=aux)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    def save_optimizer_states(self, fname):
        import pickle

        states = self._updater.get_states(dump_optimizer=False) if \
            hasattr(self._updater, "get_states") else pickle.dumps({})
        with open(fname, "wb") as f:
            f.write(states)

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            data = f.read()
        if hasattr(self._updater, "set_states"):
            self._updater.set_states(data)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from .. import serialization

        sym, arg_params, aux_params = serialization.load_checkpoint(
            prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        mod._arg_params_cache = arg_params
        mod._aux_params_cache = aux_params
        return mod

    def init_params_from_cache(self):
        if hasattr(self, "_preloaded"):
            arg, aux = self._preloaded
            self.init_params(arg_params=arg, aux_params=aux,
                             allow_missing=False, force_init=True)
