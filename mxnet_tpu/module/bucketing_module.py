"""BucketingModule: variable-length sequence training.

Reference: ``python/mxnet/module/bucketing_module.py:?`` — one Module per
bucket key, all sharing parameters; ``sym_gen(bucket_key)`` produces the
per-bucket symbol (classically unrolled RNNs fed by
``rnn/BucketSentenceIter``).

TPU-native: per-bucket modules map to per-shape XLA compilations — the
same specialization CachedOp did per (shape,dtype) — so switching buckets
is switching cached executables, with parameters shared by handle.
"""
from __future__ import annotations

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=None,
                 context=None, **module_kwargs):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._module_kwargs = module_kwargs
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._init_args = None
        self._opt_args = None

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      **self._module_kwargs)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        self._fold = (data_shapes, label_shapes)
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training=for_training,
                 inputs_need_grad=inputs_need_grad)
        self._buckets[self._default_bucket_key] = mod
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        if not self.binded:
            raise MXNetError("call bind before switch_bucket")
        if bucket_key not in self._buckets:
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes,
                     for_training=self.for_training,
                     inputs_need_grad=self.inputs_need_grad,
                     shared_module=self._buckets[self._default_bucket_key])
            self._share_params(self._buckets[self._default_bucket_key], mod)
            if self.params_initialized:
                mod.params_initialized = True
            if self.optimizer_initialized and self._opt_args:
                mod.init_optimizer(**self._opt_args)
            self._buckets[bucket_key] = mod
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    @staticmethod
    def _share_params(src, dst):
        """Alias parameter/aux NDArray handles so buckets train one set of
        weights (the reference shares executor arg arrays the same way)."""
        for name in dst._param_names:
            if name in src._exec.arg_dict:
                dst._exec.arg_dict[name] = src._exec.arg_dict[name]
                if name in src._exec.grad_dict:
                    dst._exec.grad_dict[name] = src._exec.grad_dict[name]
        for name in dst._aux_names:
            if name in src._exec.aux_dict:
                dst._exec.aux_dict[name] = src._exec.aux_dict[name]

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        assert self.binded
        self._buckets[self._default_bucket_key].init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._opt_args = dict(kvstore=kvstore, optimizer=optimizer,
                              optimizer_params=optimizer_params)
        for mod in self._buckets.values():
            mod.init_optimizer(**self._opt_args, force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", self._default_bucket_key)
        data_shapes = getattr(data_batch, "provide_data", None)
        label_shapes = getattr(data_batch, "provide_label", None)
        self.switch_bucket(key, data_shapes or self._fold[0],
                           label_shapes if label_shapes is not None
                           else self._fold[1])
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def set_params(self, arg_params, aux_params, **kwargs):
        self._buckets[self._default_bucket_key].set_params(
            arg_params, aux_params, **kwargs)
        self.params_initialized = True

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)
