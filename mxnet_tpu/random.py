"""Random number handling.

Reference: ``src/resource.cc:?`` — ops request RNG state via
``ResourceRequest::kRandom/kParallelRandom``; python seeds it through
``mx.random.seed`` (python/mxnet/random.py:?).

TPU-native redesign: jax PRNG keys.  A process-global key plays the role of
the reference's per-device random resource; every sampling call splits it.
Inside a CachedOp trace (hybridized block) keys must be *traced values*, not
Python-time constants — otherwise every call of the compiled graph would
replay the same dropout mask.  So sampling goes through ``next_key()``, which
consults a provider stack: the CachedOp installs a counter-based provider
folding indices into a base key that is an argument of the jitted function
(fresh per call), giving a deterministic number of splits per trace.
"""
from __future__ import annotations

import os
import threading
from typing import List

import numpy as np


class _KeyProvider:
    def __init__(self, base_key):
        self.base = base_key
        self.n = 0

    def next(self):
        import jax

        k = jax.random.fold_in(self.base, self.n)
        self.n += 1
        return k


class _RandState(threading.local):
    def __init__(self):
        self.key = None
        self.providers: List[_KeyProvider] = []


_STATE = _RandState()


def _global_key():
    import jax

    if _STATE.key is None:
        _STATE.key = jax.random.PRNGKey(
            int(os.environ.get("MXNET_SEED", np.random.randint(0, 2**31))))
    return _STATE.key


def seed(seed_state: int, ctx="all"):
    """Reference: ``mx.random.seed`` — also reseeds numpy-side shuffling."""
    import jax

    _STATE.key = jax.random.PRNGKey(int(seed_state))
    np.random.seed(int(seed_state) % (2**32))


def next_key():
    import jax

    if _STATE.providers:
        return _STATE.providers[-1].next()
    key, sub = jax.random.split(_global_key())
    _STATE.key = key
    return sub


class key_provider:
    """Install a counter-based key provider (used by CachedOp tracing)."""

    def __init__(self, base_key):
        self._p = _KeyProvider(base_key)

    def __enter__(self):
        _STATE.providers.append(self._p)
        return self._p

    def __exit__(self, *exc):
        _STATE.providers.pop()


# --- sampling ops (reference src/operator/random/sample_op.cc:?) ------------

def _sample(fn, shape, dtype, ctx):
    from .ndarray import NDArray

    shape = (shape,) if isinstance(shape, int) else tuple(shape or ())
    raw = fn(next_key(), shape, np.dtype(dtype or np.float32))
    out = NDArray(raw, ctx=ctx)
    return out


def uniform(low=0.0, high=1.0, shape=(1,), dtype=None, ctx=None, out=None,
            **kwargs):
    import jax

    def f(k, s, dt):
        return jax.random.uniform(k, s, dt, minval=low, maxval=high)

    r = _sample(f, shape, dtype, ctx)
    if out is not None:
        out._data = r._data
        return out
    return r


def normal(loc=0.0, scale=1.0, shape=(1,), dtype=None, ctx=None, out=None,
           **kwargs):
    import jax

    def f(k, s, dt):
        return loc + scale * jax.random.normal(k, s, dt)

    r = _sample(f, shape, dtype, ctx)
    if out is not None:
        out._data = r._data
        return out
    return r


randn = normal


def randint(low, high, shape=(1,), dtype=None, ctx=None, out=None, **kwargs):
    import jax

    def f(k, s, dt):
        return jax.random.randint(k, s, low, high,
                                  np.dtype(dtype or np.int32))

    r = _sample(f, shape, dtype or np.int32, ctx)
    if out is not None:
        out._data = r._data
        return out
    return r


def exponential(scale=1.0, shape=(1,), dtype=None, ctx=None, out=None,
                **kwargs):
    import jax

    def f(k, s, dt):
        return scale * jax.random.exponential(k, s, dt)

    return _sample(f, shape, dtype, ctx)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype=None, ctx=None, out=None,
          **kwargs):
    import jax

    def f(k, s, dt):
        return beta * jax.random.gamma(k, alpha, s, dt)

    return _sample(f, shape, dtype, ctx)


def poisson(lam=1.0, shape=(1,), dtype=None, ctx=None, out=None, **kwargs):
    import jax

    def f(k, s, dt):
        return jax.random.poisson(k, lam, s).astype(dt)

    return _sample(f, shape, dtype, ctx)


def multinomial(data, shape=1, get_prob=False, dtype=np.int32, **kwargs):
    """Sample category indices from probability rows (reference
    ``sample_multinomial``)."""
    import jax
    from .ndarray import NDArray

    n = shape if isinstance(shape, int) else int(np.prod(shape))
    logits = np.log(np.clip(data.asnumpy(), 1e-30, None))
    k = next_key()
    idx = jax.random.categorical(k, logits, axis=-1,
                                 shape=(n,) + logits.shape[:-1])
    idx = np.moveaxis(np.asarray(idx), 0, -1)
    if n == 1:
        idx = idx[..., 0]
    out = NDArray(idx.astype(dtype))
    if get_prob:
        from . import ndarray as nd

        return out, nd.log(nd.pick(data, out.astype(np.float32), axis=-1))
    return out


sample_multinomial = multinomial


def shuffle(data, **kwargs):
    import jax

    from .ops.registry import apply_op

    k = next_key()
    return apply_op(lambda a: jax.random.permutation(k, a, axis=0), data,
                    name="shuffle")


def bernoulli(prob=0.5, shape=(1,), dtype=None, ctx=None, **kwargs):
    import jax

    def f(k, s, dt):
        return jax.random.bernoulli(k, prob, s).astype(dt)

    return _sample(f, shape, dtype, ctx)
