"""Evaluation metrics.

Reference: ``python/mxnet/metric.py:?`` — ``EvalMetric`` registry with
``update(labels, preds)`` / ``get()`` / ``reset()``; the standard family
below; ``CompositeEvalMetric`` aggregates; ``create()`` builds by name.
Accumulation for the per-batch hot metrics (Accuracy/TopKAccuracy/Loss) is
DEFERRED: ``update`` reduces on device (argmax/compare/sum are enqueued
async on the dispatch stream, pulling only a running scalar — never the
full (N, C) logits) and the single blocking host sync happens at ``get``.
The reference instead copied every prediction to host per batch, which
stalls the dispatch queue once per update.  Host-rare metrics (F1, MCC,
Perplexity, ...) still accumulate on host in float64.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "PearsonCorrelation", "Loss", "Torch", "Caffe", "CustomMetric",
           "create", "np"]

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        name = metric.lower()
        aliases = {"acc": "accuracy", "ce": "crossentropy",
                   "top_k_accuracy": "topkaccuracy",
                   "top_k_acc": "topkaccuracy"}
        name = aliases.get(name, name)
        if name in _METRIC_REGISTRY:
            return _METRIC_REGISTRY[name](*args, **kwargs)
    raise MXNetError(f"unknown metric {metric!r}")


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if isinstance(labels, NDArray):
        labels = [labels]
    if isinstance(preds, NDArray):
        preds = [preds]
    if len(labels) != len(preds):
        raise MXNetError(
            f"labels/preds count mismatch: {len(labels)} vs {len(preds)}")
    return labels, preds


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict([self.get()])}"

    def get_config(self):
        config = dict(self._kwargs)
        config.update({"metric": type(self).__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names
                     if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._dev_sum = None

    def _drain(self):
        """Fold deferred device-side accumulation into ``sum_metric``.
        One host sync drains ANY number of updates; the per-update path
        never blocks the dispatch queue."""
        if getattr(self, "_dev_sum", None) is not None:
            self.sum_metric += float(self._dev_sum.asnumpy())  # mxlint: allow=T1
            self._dev_sum = None

    def _accum_device(self, scalar):
        """Add an (async, still-on-device) scalar NDArray to the running
        device accumulator."""
        self._dev_sum = scalar if self._dev_sum is None \
            else self._dev_sum + scalar

    def get(self):
        self._drain()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name)
            values.append(value)
        return (names, values)


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred, NDArray):
                # device path: argmax + compare + reduce stay on device;
                # only a running scalar survives, synced once at get()
                if pred.ndim > label.ndim:
                    pred = pred.argmax(axis=self.axis)
                correct = (pred.astype(_np.int32).reshape(-1) ==
                           label.astype(_np.int32).reshape(-1))
                self._accum_device(correct.astype(_np.float32).sum())
                self.num_inst += label.size
                continue
            label = _to_np(label)
            pred = _to_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int32).ravel()
            label = label.astype(_np.int32).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names,
                         top_k=top_k)
        self.top_k = top_k
        if top_k <= 1:
            raise MXNetError("use Accuracy for top_k=1")

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred, NDArray):
                # device path: top-k runs on device and only the (N, k)
                # indices feed the running scalar — the full logits are
                # never pulled to host
                top = pred.topk(axis=-1, k=self.top_k)
                hit = (top.astype(_np.int32).reshape(label.size, -1) ==
                       label.astype(_np.int32).reshape(-1, 1))
                self._accum_device(
                    hit.max(axis=1).astype(_np.float32).sum())
                self.num_inst += label.size
                continue
            label = _to_np(label).astype(_np.int32).ravel()
            pred = _to_np(pred)
            top = _np.argpartition(pred, -self.top_k,
                                  axis=-1)[..., -self.top_k:]
            top = top.reshape(len(label), -1)
            self.sum_metric += (top == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    """Binary F1 (reference supports macro/micro averaging)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        super().__init__(name, output_names, label_names)

    def reset(self):
        self._tp = self._fp = self._fn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).ravel().astype(_np.int32)
            pred = _to_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1)
            pred = pred.ravel().astype(_np.int32)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        precision = self._tp / max(self._tp + self._fp, 1)
        recall = self._tp / max(self._tp + self._fn, 1)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        return (self.name, f1)


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference ``mx.metric.MCC``)."""

    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def reset(self):
        self._tp = self._fp = self._fn = self._tn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).ravel().astype(_np.int32)
            pred = _to_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1)
            pred = pred.ravel().astype(_np.int32)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self._tn += ((pred == 0) & (label == 0)).sum()
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        tp, fp, fn, tn = self._tp, self._fp, self._fn, self._tn
        denom = _np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        mcc = (tp * tn - fp * fn) / denom if denom > 0 else 0.0
        return (self.name, mcc)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _to_np(label).ravel().astype(_np.int64)
            pred = _to_np(pred).reshape(len(label), -1)
            probs = pred[_np.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                probs = _np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= _np.log(_np.maximum(1e-10, probs)).sum()
            num += len(label)
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(_np.exp(self.sum_metric / self.num_inst)))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if label.ndim == pred.ndim - 1:
                label = label.reshape(pred.shape)
            self.sum_metric += _np.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if label.ndim == pred.ndim - 1:
                label = label.reshape(pred.shape)
            self.sum_metric += ((label - pred) ** 2).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(_np.sqrt(self.sum_metric / self.num_inst)))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).ravel().astype(_np.int64)
            pred = _to_np(pred).reshape(len(label), -1)
            prob = pred[_np.arange(len(label)), label]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += len(label)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).ravel()
            pred = _to_np(pred).ravel()
            self.sum_metric += _np.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Running mean of a loss output (reference ``mx.metric.Loss``)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            if isinstance(pred, NDArray):
                # device path: defer the reduction, sync once at get()
                self._accum_device(pred.astype(_np.float32).sum())
                self.num_inst += pred.size
                continue
            loss = _np.asarray(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = getattr(feval, "__name__", "custom")
            if name.startswith("<"):
                name = "custom"
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_to_np(label), _to_np(pred))
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference ``mx.metric.np``)."""
    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, name, allow_extra_outputs)
