"""Data iterators (the legacy ``mx.io`` surface).

Reference: ``python/mxnet/io/io.py:?`` (``DataIter``/``DataBatch``/
``DataDesc``, ``NDArrayIter``, ``ResizeIter``, ``PrefetchingIter``) and the
C++ iterators in ``src/io/`` (``ImageRecordIter`` —
iter_image_recordio_2.cc:?, ``CSVIter``, ``LibSVMIter``, MNISTIter).

TPU-native: iterators produce host-side numpy batches; device transfer is a
single (optionally mesh-sharded) device_put at NDArray creation — the
replacement for the reference's prefetch-to-pinned-memory path.  Threaded
prefetch replicates dmlc ThreadedIter's overlap of decode with compute.
"""
from __future__ import annotations

import functools
import os
import threading
import queue as _queue
from collections import namedtuple

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ResizeIter", "PrefetchingIter",
           "ImageRecordIter", "MNISTIter"]

#: reviewed signature budget (mxlint T15): the jitted numeric-finish
#: kernel compiles once per (batch avals, dtype) of the pipeline's
#: output spec — fixed at iterator construction, so steady state is 1
__compile_signatures__ = {
    "io_numeric_finish": "1 per (batch avals, dtype) per iterator",
}


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Shape/type descriptor (reference ``mx.io.DataDesc``)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: data list + label list + pad/index bookkeeping."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (reference ``mx.io.DataIter``): next/reset/iter_next +
    provide_data/provide_label descriptors."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError(
            "data must be NDArray, numpy.ndarray, list or dict of them")
    return [(k, np.asarray(v.asnumpy() if isinstance(v, NDArray) else v))
            for k, v in data.items()]


class NDArrayIter(DataIter):
    """Batches over in-memory arrays with shuffle/pad/discard last-batch
    handling (reference ``mx.io.NDArrayIter``)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         dtype=v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         dtype=v.dtype) for k, v in self.label]

    def reset(self):
        self.cursor = 0
        if self.shuffle:
            self.order = np.random.permutation(self.num_data)
        else:
            self.order = np.arange(self.num_data)

    def iter_next(self):
        return self.cursor < self.num_batches * self.batch_size and \
            self.cursor < self.num_data if \
            self.last_batch_handle != "discard" else \
            self.cursor + self.batch_size <= self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        lo = self.cursor
        hi = min(lo + self.batch_size, self.num_data)
        idx = self.order[lo:hi]
        pad = self.batch_size - len(idx)
        if pad and self.last_batch_handle == "pad":
            idx = np.concatenate([idx, self.order[:pad]])
        self.cursor += self.batch_size
        data = [NDArray(arr[idx]) for _, arr in self.data]
        label = [NDArray(arr[idx]) for _, arr in self.label]
        return DataBatch(data=data, label=label or None, pad=pad,
                         index=idx,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def getpad(self):
        return 0


class CSVIter(DataIter):
    """CSV reader (reference C++ ``CSVIter``, src/io/iter_csv.cc:?)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32,
                          ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """LibSVM text reader → CSR batches (reference C++ ``LibSVMIter``,
    src/io/iter_libsvm.cc:? — the sparse pipeline feeding the
    factorization-machine / linear-model workloads, SURVEY §2.5)."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, round_batch=True, **kwargs):
        super().__init__(batch_size)
        self._num_features = int(data_shape[0]) \
            if isinstance(data_shape, (tuple, list)) else int(data_shape)
        labels = []
        indices, values = [], []
        indptr = [0]
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    indices.append(int(i))
                    values.append(float(v))
                indptr.append(len(indices))
        if label_libsvm is not None:
            # separate label file overrides the data file's lead column
            # (reference LibSVMIter contract)
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        labels.append(float(parts[0]))
            if len(labels) != len(indptr) - 1:
                raise MXNetError(
                    f"label file has {len(labels)} rows but data file has "
                    f"{len(indptr) - 1}")
        self._labels = np.asarray(labels, np.float32)
        self._indptr = np.asarray(indptr, np.int64)
        self._indices = np.asarray(indices, np.int64)
        self._values = np.asarray(values, np.float32)
        self._n = len(labels)
        self._cursor = 0
        self._round = round_batch

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_features))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def _row_slice(self, lo, hi):
        from ..ndarray import sparse as sp

        start, end = self._indptr[lo], self._indptr[hi]
        indptr = self._indptr[lo:hi + 1] - start
        return sp.CSRNDArray(self._values[start:end],
                             self._indices[start:end], indptr,
                             (hi - lo, self._num_features))

    def next(self):
        if self._cursor >= self._n:
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._n)
        pad = self.batch_size - (hi - lo)
        if pad and not self._round:
            raise StopIteration
        csr = self._row_slice(lo, hi)
        label = self._labels[lo:hi]
        if pad:
            # wrap around (reference round_batch contract); loop covers
            # batch_size > dataset size
            from ..ndarray import sparse as sp

            data = [np.asarray(csr.data._data)]
            indices = [np.asarray(csr.indices._data)]
            indptr = np.asarray(csr.indptr._data)
            labels = [label]
            remaining = pad
            while remaining > 0:
                take = min(remaining, self._n)
                extra = self._row_slice(0, take)
                data.append(np.asarray(extra.data._data))
                indices.append(np.asarray(extra.indices._data))
                indptr = np.concatenate(
                    [indptr,
                     np.asarray(extra.indptr._data)[1:] + indptr[-1]])
                labels.append(self._labels[:take])
                remaining -= take
            csr = sp.CSRNDArray(np.concatenate(data),
                                np.concatenate(indices), indptr,
                                (self.batch_size, self._num_features))
            label = np.concatenate(labels)
        self._cursor = hi
        return DataBatch(data=[csr], label=[NDArray(label)], pad=pad)


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (reference ``mx.io.ResizeIter``)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label


class PrefetchingIter(DataIter):
    """Threaded prefetch decorator (reference ``mx.io.PrefetchingIter`` /
    dmlc ThreadedIter — overlaps host decode with device compute)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        assert len(iters) == 1, "single-iter prefetch (reference parity)"
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._depth = prefetch_depth
        self._queue = None
        self._thread = None
        self._start()

    def _start(self):
        self._queue = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()

        def worker():
            while not self._stop.is_set():
                try:
                    batch = self.iter.next()
                except StopIteration:
                    self._queue.put(None)
                    return
                except Exception as e:  # propagate errors to consumer
                    self._queue.put(e)
                    return
                self._queue.put(batch)

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="mxt-io-prefetch")
        self._thread.start()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        self._thread.join(timeout=5)
        self.iter.reset()
        self._start()

    def next(self):
        item = self._queue.get()
        if item is None:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label


@functools.lru_cache(maxsize=16)  # bounded: one executable per config
def _numeric_finish(mean, std, scale):
    """One shared jitted cast+normalize+CHW program per (mean, std,
    scale) config — train/val iterator pairs reuse a single compile."""
    import jax
    import jax.numpy as jnp

    mean_a = np.asarray(mean, np.float32)
    std_a = np.asarray(std, np.float32)

    def f(x):  # (B, H, W, C) uint8
        y = x.astype(jnp.float32)
        if scale != 1.0:
            y = y * scale
        if mean_a.any():
            y = y - mean_a
        if (std_a != 1).any():
            y = y / std_a
        return jnp.transpose(y, (0, 3, 1, 2))

    return jax.jit(f)


class ImageRecordIter(DataIter):
    """RecordIO image pipeline: shard-read → decode → augment → batch →
    prefetch (reference C++ ``ImageRecordIter``,
    src/io/iter_image_recordio_2.cc:? — here a python pipeline over the
    byte-compatible recordio reader with cv2 decode)."""

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 rand_crop=False, rand_mirror=False, resize=-1,
                 path_imgidx=None, num_parts=1, part_index=0,
                 preprocess_threads=2, prefetch_buffer=2,
                 round_batch=True, seed=0, **kwargs):
        super().__init__(batch_size)
        from .. import recordio

        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._rng = np.random.RandomState(seed)
        self._aug = dict(mean=(mean_r, mean_g, mean_b),
                         std=(std_r, std_g, std_b), scale=scale,
                         rand_crop=rand_crop, rand_mirror=rand_mirror,
                         resize=resize)
        from .. import _native

        self._pf = None
        self._records = None
        if _native.available() and not kwargs.get("no_native"):
            # native streaming path: C++ indexed reader + engine-scheduled
            # batch prefetch (src/cpp/mxt_recordio.cc); records stay on
            # disk, batches are read by worker threads ahead of consumption.
            # One prefetcher lives for the iterator's lifetime (the index
            # scan + thread pool happen once, not per epoch).
            self._cap = max(int(prefetch_buffer), 1)
            self._pf = _native.Prefetcher(path_imgrec,
                                          nthreads=preprocess_threads,
                                          capacity=self._cap)
            self._sched = self._consumed = 0
            self._batches = []
            if path_imgidx and os.path.isfile(path_imgidx):
                # honour the .idx: shard by KEY order (which may be a
                # pre-shuffle or a subset), mapping byte offsets to the
                # reader's scan-order indices
                off2pos = {self._pf._reader.offset(i): i
                           for i in range(len(self._pf))}
                positions = []
                with open(path_imgidx) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        if len(parts) >= 2:
                            positions.append(off2pos[int(parts[1])])
                self._indices = np.asarray(
                    positions[part_index::num_parts], dtype=np.int64)
            else:
                self._indices = np.arange(
                    len(self._pf))[part_index::num_parts]
        else:
            # pure-python fallback: load the shard's records into memory
            if path_imgidx:
                rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                                 "r")
                keys = rec.keys
            else:
                rec = recordio.MXRecordIO(path_imgrec, "r")
                keys = None
            self._records = []
            if keys is not None:
                use = keys[part_index::num_parts]
                for k in use:
                    self._records.append(rec.read_idx(k))
            else:
                i = 0
                while True:
                    payload = rec.read()
                    if payload is None:
                        break
                    if i % num_parts == part_index:
                        self._records.append(payload)
                    i += 1
            rec.close()
        self.shuffle = shuffle
        self.round_batch = round_batch
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def _plan_batches(self, order):
        """Split an epoch order into (index_array, pad) batch plans;
        wrap-around padding tiles the order (shards smaller than one batch
        still fill it)."""
        plans = []
        n = len(order)
        for s in range(0, n, self.batch_size):
            idx = order[s:s + self.batch_size]
            pad = self.batch_size - len(idx)
            if pad:
                if not self.round_batch:
                    break
                idx = np.concatenate([idx, np.resize(order, pad)])
            plans.append((idx, pad))
        return plans

    def reset(self):
        if self._pf is not None:
            # drain batches scheduled but unconsumed (early reset)
            while self._consumed < self._sched:
                self._pf.next()
                self._consumed += 1
            order = self._indices.copy()
            if self.shuffle:
                self._rng.shuffle(order)
            self._batches = self._plan_batches(order)
            self._sched = self._consumed = 0
            while self._sched < min(len(self._batches), self._cap + 1):
                self._pf.schedule(self._batches[self._sched][0])
                self._sched += 1
        else:
            order = np.arange(len(self._records))
            if self.shuffle:
                self._rng.shuffle(order)
            self._batches = self._plan_batches(order)
            self._consumed = 0

    def _device_finish(self):
        """Numeric augmentation stage, ON DEVICE: batches cross host→HBM
        as HWC uint8 (4× less transfer than float32 CHW — measured 4×
        throughput through the remote tunnel), then one jitted
        cast+normalize+transpose runs where the bandwidth is."""
        return _numeric_finish(tuple(self._aug["mean"]),
                               tuple(self._aug["std"]),
                               float(self._aug["scale"]))

    def _make_batch(self, payloads, pad):
        from .. import recordio
        from ..image import augment_geom, imdecode_raw

        datas, labels = [], []
        for payload in payloads:
            header, img_bytes = recordio.unpack(payload)
            img = imdecode_raw(img_bytes)
            img = augment_geom(img, self.data_shape, self._rng,
                               rand_crop=self._aug["rand_crop"],
                               rand_mirror=self._aug["rand_mirror"],
                               resize=self._aug["resize"])
            datas.append(img)
            label = header.label
            if isinstance(label, np.ndarray) and self.label_width == 1:
                label = label[0] if label.size else 0.0
            labels.append(label)
        batch_u8 = NDArray(np.stack(datas))
        data = NDArray(self._device_finish()(batch_u8._data))
        label = NDArray(np.asarray(labels, dtype=np.float32))
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def next(self):
        if self._consumed >= len(self._batches):
            raise StopIteration
        idx, pad = self._batches[self._consumed]
        self._consumed += 1
        if self._pf is not None:
            payloads = self._pf.next()
            if self._sched < len(self._batches):
                self._pf.schedule(self._batches[self._sched][0])
                self._sched += 1
        else:
            payloads = [self._records[i] for i in idx]
        return self._make_batch(payloads, pad)


class MNISTIter(NDArrayIter):
    """MNIST idx-format reader (reference src/io/iter_mnist.cc:?)."""

    def __init__(self, image, label, batch_size=128, shuffle=True,
                 flat=False, **kwargs):
        import gzip
        import struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                magic = struct.unpack(">I", f.read(4))[0]
                ndim = magic & 0xFF
                dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)

        images = read_idx(image).astype(np.float32) / 255.0
        labels = read_idx(label).astype(np.float32)
        if flat:
            images = images.reshape(len(images), -1)
        else:
            images = images.reshape(len(images), 1, *images.shape[1:])
        super().__init__(images, labels, batch_size, shuffle=shuffle,
                         last_batch_handle="discard")
