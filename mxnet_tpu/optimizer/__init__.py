"""Optimizers.

Reference: ``python/mxnet/optimizer/optimizer.py:?`` (Optimizer registry,
lr/wd multipliers, update-count tracking, multi-precision) over the fused
update ops in ``src/operator/optimizer_op.cc:?`` (``sgd_update``,
``sgd_mom_update``, ``mp_sgd_*``, ``adam_update``, ``lamb_*``, ...).  The
key reference invariant: optimizer math runs *as engine ops on device*, not
in python.

TPU-native redesign: each optimizer's update is a pure function jitted once
per (shape, dtype) — the XLA analog of the fused update kernels.  Learning
rate / weight decay enter as traced scalars so per-step schedule changes do
NOT recompile.  Multi-precision keeps an fp32 master weight in the state,
exactly like ``mp_sgd_mom_update``.  Sparse (row_sparse) lazy updates are
routed through ``_sparse_step`` where defined (SURVEY §2.2 optimizer-ops
row; sparse path in mxnet_tpu/ndarray/sparse.py).
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from .. import sanitizer as _san
from ..telemetry import costs as _costs
from ..telemetry import memwatch as _mw

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "LAMB", "RMSProp",
           "AdaGrad", "AdaDelta", "Ftrl", "Signum", "SignSGD", "LARS",
           "create", "register", "Test", "Updater", "get_updater"]

#: reviewed signature budget (mxlint T15): the per-param jitted update
#: compiles one program per (optimizer type, precision path, weight
#: shape, dtype) — parameter count does not grow signatures, distinct
#: shapes do
__compile_signatures__ = {
    "optimizer_update": "2 per (optimizer, weight shape, dtype): "
                        "sp + mp paths",
}

#: donation-sanitizer site tag for the per-param jitted update
_PER_PARAM_SITE = ("Optimizer._update_impl (mxnet_tpu/optimizer, %s "
                   "per-param update, donate_argnums=(0, 2))")


def _f32(x):
    return x.astype(np.float32) if x.dtype != np.float32 else x


def _state_zeros(weight, dtype=None):
    """Zeros matching the weight's shape AND device/mesh placement, so
    optimizer state lives wherever the parameter lives (replicated or
    sharded over the mesh)."""
    import jax
    import jax.numpy as jnp

    raw = jnp.zeros(weight.shape, dtype or weight.dtype)
    try:
        raw = jax.device_put(raw, weight._data.sharding)
    except Exception:
        pass
    return NDArray(raw)


class Optimizer:
    """Base optimizer (reference: ``mx.optimizer.Optimizer``)."""

    opt_registry = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=0, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._all_index_update_counts = {0: {}}
        self._index_update_count = self._all_index_update_counts[0]
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = dict(param_idx2name)
        self.param_dict = param_dict if param_dict else {}
        self.lr_mult = {}
        self.wd_mult = {}
        self._jit_cache = {}

    # -- registry ------------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() not in Optimizer.opt_registry:
            raise MXNetError(f"unknown optimizer {name!r}; registered: "
                             f"{sorted(Optimizer.opt_registry)}")
        return Optimizer.opt_registry[name.lower()](**kwargs)

    # -- lr/wd ---------------------------------------------------------------
    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError(
                "cannot set learning rate: an LRScheduler is active")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else \
            self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _set_current_context(self, device_id):
        if device_id not in self._all_index_update_counts:
            self._all_index_update_counts[device_id] = {}
        self._index_update_count = self._all_index_update_counts[device_id]

    # -- state ---------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and np.dtype(weight.dtype).name in (
                "float16", "bfloat16"):
            master = NDArray(_f32(weight._data))
            return (master, self.create_state(index, master))
        if np.dtype(weight.dtype).name in ("float16", "bfloat16") and \
                not self.multi_precision:
            import warnings

            warnings.warn(
                "reduced-precision weights without multi_precision=True may "
                "be poorly conditioned; consider multi_precision=True")
        return self.create_state(index, weight)

    # -- update --------------------------------------------------------------
    def _step(self, w, g, states, lr, wd, t):
        """Pure update math: raw arrays in → (new_w, new_states).  Subclasses
        implement; traced once per shape (the fused-kernel analog)."""
        raise NotImplementedError

    def _prep_grad(self, g, w, wd, include_wd=True):
        import jax.numpy as jnp

        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        if include_wd:
            g = g + wd * w
        return g

    def _jitted(self, key, fn, donate=()):
        import jax

        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(fn, donate_argnums=donate)
        return self._jit_cache[key]

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and np.dtype(weight.dtype).name in (
            "float16", "bfloat16")
        self._update_impl(index, weight, grad, state, multi_precision=use_mp)

    def _update_impl(self, index, weight, grad, state, multi_precision):
        if isinstance(index, (list, tuple)):
            for i, w, g, s in zip(index, weight, grad, state):
                self._update_impl(i, w, g, s, multi_precision)
            return
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]

        # sparse lazy update path (row_sparse grads touch only live rows —
        # reference: lazy_update in optimizer ops)
        from ..ndarray import sparse as sp

        if isinstance(grad, sp.RowSparseNDArray) and \
                hasattr(self, "_sparse_step"):
            self._sparse_step(index, weight, grad, state, lr, wd, t)
            return
        if isinstance(grad, sp.BaseSparseNDArray):
            grad = grad.tostype("default")

        # weight/master and state buffers are donated (argnums 0 and 2):
        # the update is in place on device, matching the fused
        # multi-tensor path's donation contract.  Grads stay read-only.
        if multi_precision:
            master, sub_state = state
            step = self._jitted(
                ("mp", weight.shape, str(weight.dtype)),
                lambda mw, g, ss, lr_, wd_, t_: self._step(
                    mw, _f32(g), ss, lr_, wd_, t_),
                donate=(0, 2))
            states = tuple(s._data for s in _flatten_state(sub_state))
            old = (master._data,) + states
            if _costs._enabled:
                _costs.note(
                    "optimizer_update",
                    (id(self), "mp", weight.shape, str(weight.dtype)),
                    step, (master._data, grad._data, states, lr, wd, t),
                    site="mxnet_tpu.optimizer:Optimizer.update")
            new_w, new_states = step(master._data, grad._data, states,
                                     lr, wd, t)
            if _san._enabled:
                _san.donate(old, _PER_PARAM_SITE % type(self).__name__)
            if _mw._enabled:
                _mw.donated(old)
            master._data = new_w
            weight._data = new_w.astype(weight.dtype)
            _commit_state(sub_state, new_states)
        else:
            step = self._jitted(
                ("sp", weight.shape, str(weight.dtype)),
                lambda w, g, ss, lr_, wd_, t_: self._step(
                    w, g, ss, lr_, wd_, t_),
                donate=(0, 2))
            states = tuple(s._data for s in _flatten_state(state))
            old = (weight._data,) + states
            if _costs._enabled:
                _costs.note(
                    "optimizer_update",
                    (id(self), "sp", weight.shape, str(weight.dtype)),
                    step, (weight._data, grad._data, states, lr, wd, t),
                    site="mxnet_tpu.optimizer:Optimizer.update")
            new_w, new_states = step(weight._data, grad._data, states,
                                     lr, wd, t)
            if _san._enabled:
                _san.donate(old, _PER_PARAM_SITE % type(self).__name__)
            if _mw._enabled:
                _mw.donated(old)
            weight._data = new_w
            _commit_state(state, new_states)


def _flatten_state(state):
    if state is None:
        return ()
    if isinstance(state, NDArray):
        return (state,)
    out = []
    for s in state:
        out.extend(_flatten_state(s))
    return tuple(out)


def _commit_state(state, new_raws):
    holders = _flatten_state(state)
    for h, r in zip(holders, new_raws):
        h._data = r


def _fused_param_updates(optzr, mp_flags, w_raws, m_raws, g_raws, s_raws,
                         lr_v, wd_v, t_v):
    """One traced optimizer step across all params — the shared body of
    the Trainer's fused multi-tensor update and FusedTrainStep's scan
    (one contract, two dispatch shapes).  ``m_raws`` holds ONLY the
    multi-precision masters, keyed by position among mp params — never
    an alias of a donated weight buffer.  ``t_v`` may be per-param ints
    or a traced int vector.  Returns (new_w, new_m, new_s) tuples."""
    import numpy as _np

    new_w, new_m, new_s = [], [], []
    mi = 0
    for j in range(len(mp_flags)):
        if mp_flags[j]:
            nw, ns = optzr._step(m_raws[mi],
                                 g_raws[j].astype(_np.float32),
                                 s_raws[j], lr_v[j], wd_v[j], t_v[j])
            mi += 1
            new_m.append(nw)
            new_w.append(nw.astype(w_raws[j].dtype))
        else:
            nw, ns = optzr._step(w_raws[j], g_raws[j], s_raws[j],
                                 lr_v[j], wd_v[j], t_v[j])
            new_w.append(nw)
        new_s.append(ns)
    return tuple(new_w), tuple(new_m), tuple(new_s)


def _commit_param_updates(trainer, live, mp_flags, masters, new_w, new_m,
                          new_s):
    """Write a fused update's results back into the trainer's params,
    masters and optimizer state holders (shared by Trainer._update and
    FusedTrainStep)."""
    mi = 0
    for j, i in enumerate(live):
        param = trainer._params[i]
        param.data()._data = new_w[j]
        if mp_flags[j]:
            masters[j]._data = new_m[mi]
            mi += 1
            sub_state = trainer._states[i][1]
        else:
            sub_state = trainer._states[i]
        _commit_state(sub_state, new_s[j])


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum + multi-precision (reference ``sgd_update`` /
    ``sgd_mom_update`` / ``mp_sgd_*``, src/operator/optimizer_op.cc:?)."""

    def __init__(self, momentum=0.0, lazy_update=True, learning_rate=None,
                 **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.01, **kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _state_zeros(
            weight, np.float32 if np.dtype(weight.dtype).name in
            ("float16", "bfloat16") else weight.dtype)

    def _step(self, w, g, states, lr, wd, t):
        g = self._prep_grad(g.astype(w.dtype), w, wd)
        if self.momentum == 0.0:
            return w - lr * g, ()
        (mom,) = states
        mom = self.momentum * mom - lr * g.astype(mom.dtype)
        return w + mom.astype(w.dtype), (mom,)

    def _sparse_step(self, index, weight, grad, state, lr, wd, t):
        """Lazy row_sparse update: only rows present in the gradient are
        touched (reference: ``sgd_update(lazy_update=True)``)."""
        import jax.numpy as jnp

        idx, vals = grad.indices._data, grad.data._data
        w = weight._data
        g = vals * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        rows = w[idx]
        g = g + wd * rows
        if self.momentum == 0.0:
            weight._data = w.at[idx].add((-lr * g).astype(w.dtype))
        else:
            mom = state._data
            new_rows_mom = self.momentum * mom[idx] - lr * g
            state._data = mom.at[idx].set(new_rows_mom)
            weight._data = w.at[idx].add(new_rows_mom.astype(w.dtype))


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference ``nag_mom_update``)."""

    def __init__(self, momentum=0.0, learning_rate=None, **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.01, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _state_zeros(weight)

    def _step(self, w, g, states, lr, wd, t):
        g = self._prep_grad(g.astype(w.dtype), w, wd)
        if self.momentum == 0.0:
            return w - lr * g, ()
        (mom,) = states
        mom = self.momentum * mom + g
        return w - lr * (g + self.momentum * mom), (mom,)


@register
class Adam(Optimizer):
    """Adam (reference ``adam_update``; default lr 0.001)."""

    def __init__(self, learning_rate=None, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.001, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        dt = np.float32 if np.dtype(weight.dtype).name in (
            "float16", "bfloat16") else weight.dtype
        return (_state_zeros(weight, dt), _state_zeros(weight, dt))

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        m, v = states
        g = self._prep_grad(g.astype(m.dtype), w.astype(m.dtype), wd)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * jnp.sqrt(coef2) / coef1
        new_w = w - (lr_t * m / (jnp.sqrt(v) + self.epsilon)).astype(w.dtype)
        return new_w, (m, v)

    def _sparse_step(self, index, weight, grad, state, lr, wd, t):
        import jax.numpy as jnp

        m, v = state
        idx, vals = grad.indices._data, grad.data._data
        w = weight._data
        g = vals * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * w[idx]
        m_rows = self.beta1 * m._data[idx] + (1 - self.beta1) * g
        v_rows = self.beta2 * v._data[idx] + (1 - self.beta2) * jnp.square(g)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * jnp.sqrt(coef2) / coef1
        m._data = m._data.at[idx].set(m_rows)
        v._data = v._data.at[idx].set(v_rows)
        weight._data = w.at[idx].add(
            (-lr_t * m_rows / (jnp.sqrt(v_rows) + self.epsilon)
             ).astype(w.dtype))


@register
class AdamW(Adam):
    """Adam with decoupled weight decay (reference contrib ``adamw_update``,
    src/operator/contrib/adamw.cc:?)."""

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        m, v = states
        g = self._prep_grad(g.astype(m.dtype), w.astype(m.dtype), 0.0,
                            include_wd=False)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * jnp.sqrt(coef2) / coef1
        upd = lr_t * m / (jnp.sqrt(v) + self.epsilon) + lr * wd * w.astype(
            m.dtype)
        return w - upd.astype(w.dtype), (m, v)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (reference ``lamb_update_
    phase1/2``, src/operator/optimizer_op.cc:? — the BERT-large optimizer)."""

    def __init__(self, learning_rate=None, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.001, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        dt = np.float32 if np.dtype(weight.dtype).name in (
            "float16", "bfloat16") else weight.dtype
        return (_state_zeros(weight, dt), _state_zeros(weight, dt))

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        m, v = states
        wf = w.astype(m.dtype)
        g = self._prep_grad(g.astype(m.dtype), wf, 0.0, include_wd=False)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        if self.bias_correction:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        gprime = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * wf
        r1 = jnp.linalg.norm(wf)
        if self.lower_bound is not None:
            r1 = jnp.maximum(r1, self.lower_bound)
        if self.upper_bound is not None:
            r1 = jnp.minimum(r1, self.upper_bound)
        r2 = jnp.linalg.norm(gprime)
        ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
        return w - (lr * ratio * gprime).astype(w.dtype), (m, v)


@register
class RMSProp(Optimizer):
    """RMSProp, centered and plain (reference ``rmsprop_update`` /
    ``rmspropalex_update``)."""

    def __init__(self, learning_rate=None, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.001, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_state_zeros(weight), _state_zeros(weight),
                    _state_zeros(weight))
        return (_state_zeros(weight),)

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = self._prep_grad(g.astype(w.dtype), w, wd)
        if not self.centered:
            (n,) = states
            n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            new_w = w - lr * g / jnp.sqrt(n + self.epsilon)
        else:
            n, gbar, delta = states
            n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            gbar = (1 - self.gamma1) * g + self.gamma1 * gbar
            delta = self.gamma2 * delta - lr * g / jnp.sqrt(
                n - jnp.square(gbar) + self.epsilon)
            new_w = w + delta
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        return new_w, (n,) if not self.centered else (n, gbar, delta)


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=None, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.01, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _state_zeros(weight)

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        (hist,) = states
        g = self._prep_grad(g.astype(w.dtype), w, wd)
        hist = hist + jnp.square(g)
        return w - lr * g / (jnp.sqrt(hist) + self.float_stable_eps), (hist,)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_state_zeros(weight), _state_zeros(weight))

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        acc_g, acc_delta = states
        g = self._prep_grad(g.astype(w.dtype), w, wd)
        acc_g = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta + self.epsilon) / jnp.sqrt(
            acc_g + self.epsilon) * g
        acc_delta = self.rho * acc_delta + (1 - self.rho) * jnp.square(delta)
        return w - delta, (acc_g, acc_delta)


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference ``ftrl_update`` — the sparse-friendly
    L1-regularized optimizer for the factorization-machine config)."""

    def __init__(self, lamda1=0.01, learning_rate=None, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.1, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_state_zeros(weight),  # z
                _state_zeros(weight))  # n

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        z, n = states
        g = self._prep_grad(g.astype(w.dtype), w, 0.0, include_wd=False)
        sq = jnp.square(g)
        sigma = (jnp.sqrt(n + sq) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + sq
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1) /
            ((self.beta + jnp.sqrt(n)) / lr + wd),
            jnp.zeros_like(w))
        return new_w, (z, n)


@register
class Signum(Optimizer):
    """signSGD with momentum (reference ``signum_update``)."""

    def __init__(self, learning_rate=None, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.01, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _state_zeros(weight)

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        g = self._prep_grad(g.astype(w.dtype), w, wd)
        if self.momentum == 0.0:
            return w - lr * jnp.sign(g), ()
        (mom,) = states
        mom = self.momentum * mom - (1 - self.momentum) * g
        new_w = (1 - lr * self.wd_lh) * w + lr * jnp.sign(mom)
        return new_w, (mom,)


@register
class SignSGD(Signum):
    def __init__(self, learning_rate=None, **kwargs):
        super().__init__(learning_rate=learning_rate, momentum=0.0, **kwargs)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference contrib ``lars``-flavoured
    multi_sgd path; large-batch ResNet optimizer)."""

    def __init__(self, learning_rate=None, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate
                         if learning_rate is not None else 0.1, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return _state_zeros(weight)

    def _step(self, w, g, states, lr, wd, t):
        import jax.numpy as jnp

        (mom,) = states
        g = self._prep_grad(g.astype(w.dtype), w, 0.0, include_wd=False)
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        g = g + wd * w
        mom = self.momentum * mom + lr * trust * g
        return w - mom, (mom,)


@register
class Test(Optimizer):
    """Reference test optimizer: w -= lr * grad, no frills."""

    def create_state(self, index, weight):
        return None

    def _step(self, w, g, states, lr, wd, t):
        return w - lr * (g * self.rescale_grad).astype(w.dtype), ()


class Updater:
    """Applies an optimizer imperatively per (index, grad, weight) triple —
    the reference's kvstore-side updater closure (``mx.optimizer.
    get_updater``, used by ``update_on_kvstore=True``)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle

        states = {k: _states_to_numpy(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((states, _OptimizerConfig(self.optimizer)))
        return pickle.dumps(states)

    def set_states(self, states):
        import pickle

        loaded = pickle.loads(states)
        if isinstance(loaded, tuple):
            loaded = loaded[0]
        self.states = {k: _states_from_numpy(v) for k, v in loaded.items()}
        self.states_synced = {k: True for k in self.states}


class _OptimizerConfig:
    def __init__(self, opt):
        self.name = type(opt).__name__.lower()


def _states_to_numpy(state):
    if state is None:
        return None
    if isinstance(state, NDArray):
        return state.asnumpy()
    return tuple(_states_to_numpy(s) for s in state)


def _states_from_numpy(state):
    if state is None:
        return None
    if isinstance(state, np.ndarray):
        return NDArray(state)
    return tuple(_states_from_numpy(s) for s in state)


def get_updater(optimizer):
    return Updater(optimizer)
