"""mxnet_tpu — a TPU-native framework with the capabilities of Apache MXNet.

Built from scratch against the architecture documented in /root/repo/SURVEY.md
(reference: Kh4L/incubator-mxnet, an apache/incubator-mxnet 1.x fork).  The
compute path is jax/XLA/Pallas; the user API preserves MXNet semantics:
``mx.nd.*`` imperative NDArrays, ``autograd.record()``, Gluon
``Block/HybridBlock/Trainer``, ``KVStore`` — extended with ``mx.tpu()``
contexts, a ``dist_tpu_sync`` KVStore mode (psum over the ICI mesh), and
sequence/tensor parallelism the reference never had.

Typical use (identical to reference scripts, one-line context swap):

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd, nd

    ctx = mx.tpu()
    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1}, kvstore='dist_tpu_sync')
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(batch_size)
"""

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from .context import Context, cpu, tpu, gpu, current_context, num_gpus, \
    num_tpus, num_devices
from . import ndarray
from . import ndarray as nd  # canonical alias, reference: `mx.nd`
from .ndarray import NDArray
from . import autograd
from . import random
from . import ops
from . import engine

# subsystems imported lazily on attribute access to keep `import mxnet_tpu`
# fast (the reference generates op wrappers at import; we defer heavyweight
# subpackages instead)
_LAZY = {
    "symbol": ".symbol",
    "sym": ".symbol",
    "module": ".module",
    "mod": ".module",
    "operator": ".operator",
    "rtc": ".rtc",
    "executor": ".executor",
    "name": ".name",
    "gluon": ".gluon",
    "optimizer": ".optimizer",
    "kvstore": ".kvstore",
    "kv": ".kvstore",
    "initializer": ".initializer",
    "init": ".initializer",
    "metric": ".metric",
    "lr_scheduler": ".lr_scheduler",
    "callback": ".callback",
    "io": ".io",
    "rnn": ".rnn",
    "image": ".image",
    "parallel": ".parallel",
    "profiler": ".profiler",
    "telemetry": ".telemetry",
    "monitor": ".monitor",
    "visualization": ".visualization",
    "viz": ".visualization",
    "recordio": ".recordio",
    "serialization": ".serialization",
    "amp": ".amp",
    "contrib": ".contrib",
    "test_utils": ".test_utils",
    "numpy": ".numpy",
    "np": ".numpy",
    "numpy_extension": ".numpy_extension",
    "npx": ".numpy_extension",
    "util": ".util",
    "runtime": ".runtime",
    "models": ".models",
    "model": ".model",
    "predictor": ".predictor",
    "checkpoint": ".checkpoint",
    "elastic": ".elastic",
    "serving": ".serving",
    "data": ".data",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# profiler autostart must defeat the lazy import (reference profiles from
# process start when MXNET_PROFILER_AUTOSTART=1, SURVEY §5)
import os as _os

if _os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    from . import profiler  # noqa: F401  (its import-time hook starts it)
