"""Runtime kernel compilation.

Reference: ``python/mxnet/rtc.py:?`` — ``CudaModule``/``CudaKernel`` wrap
NVRTC to compile CUDA C at runtime and launch it on NDArrays (SURVEY §2.4
misc row).

TPU-native: there is no CUDA C on TPU; the runtime-kernel story is
**Pallas**.  ``PallasKernel`` wraps a user-supplied pallas kernel function
into an NDArray-level op on the same dispatch/autograd machinery every
built-in op uses — the role ``CudaModule.get_kernel().launch`` played.
``CudaModule`` raises with guidance instead of silently missing.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule", "PallasKernel"]


class CudaModule:
    def __init__(self, *args, **kwargs):
        raise MXNetError(
            "CUDA runtime compilation does not exist on TPU; write a "
            "Pallas kernel (jax.experimental.pallas) and wrap it with "
            "mxnet_tpu.rtc.PallasKernel")


class PallasKernel:
    """Wrap a jax/pallas callable into an ``mx.nd`` op.

    ``fn(*raw_arrays) -> raw array (or tuple)`` — typically a
    ``pl.pallas_call`` closure.  The wrapper routes through ``apply_op``
    so autograd taping, AMP casts and profiler events all apply.
    """

    def __init__(self, fn, name=None):
        self._fn = fn
        self._name = name or getattr(fn, "__name__", "pallas_kernel")

    def launch(self, *args, **const):
        from .ops.registry import apply_op

        if const:
            fn = self._fn

            def bound(*raws):
                return fn(*raws, **const)

            return apply_op(bound, *args, name=self._name)
        return apply_op(self._fn, *args, name=self._name)

    __call__ = launch
