"""Device contexts: ``mx.cpu()``, ``mx.tpu()``, ``mx.gpu()``.

Reference: ``python/mxnet/context.py:?`` — ``Context(device_type, device_id)``
with a thread-local "current context" stack used as the default placement for
every NDArray creation.

TPU-native redesign: a Context resolves to a concrete ``jax.Device``.  The
north star extends the reference's {cpu, gpu} pair with ``mx.tpu()``;
``mx.gpu()`` is kept as a compatibility alias that maps to the accelerator
backend when one exists (so reference scripts that say ``ctx=mx.gpu(0)`` run
unchanged on a TPU host).  Multi-device placement for data-parallel training
is a *list* of contexts, exactly like the reference's ``ctx=[mx.gpu(i) ...]``;
the parallel layer (mxnet_tpu/parallel) turns such lists into a
``jax.sharding.Mesh``.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from .base import MXNetError


class Context:
    """A device context.

    Parameters
    ----------
    device_type : str
        'cpu', 'tpu' or 'gpu' ('gpu' aliases the default jax accelerator).
    device_id : int
        Index into this process's ``jax.local_devices(backend)``.
    """

    _local = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in ("cpu", "tpu", "gpu"):
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- jax resolution ------------------------------------------------------
    @property
    def device(self):
        """Resolve to the concrete jax.Device (lazy: jax initialises backends
        on first use)."""
        import jax

        # LOCAL devices only: in a multi-process group jax.devices() is
        # the global list, and a context on another host's device would
        # device_put to a non-addressable target (and desync the
        # process-collective bookkeeping).  Single-process, local==global.
        if self.device_type == "cpu":
            devs = jax.local_devices(backend="cpu")
        else:
            # 'tpu' and the 'gpu' compat alias both mean "the accelerator
            # backend jax booted with" — under JAX_PLATFORMS=cpu that is the
            # (virtual) CPU device list, which is exactly what the unit-test
            # mesh wants.
            devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                f"context {self} out of range: only {len(devs)} "
                f"device(s) available"
            )
        return devs[self.device_id]

    # -- identity ------------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    # -- default-context stack ----------------------------------------------
    def __enter__(self):
        stack = getattr(Context._local, "stack", None)
        if stack is None:
            stack = Context._local.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._local.stack.pop()

    @staticmethod
    def default_ctx() -> "Context":
        stack = getattr(Context._local, "stack", None)
        if stack:
            return stack[-1]
        return _default_context()


def _default_context() -> Context:
    """The process default: the accelerator if jax has one, else cpu.

    (Reference defaults to cpu(0); we default to the TPU when present because
    that is the whole point of the port — override with ``with mx.cpu():``.)
    """
    import jax

    platform = jax.default_backend()
    if platform == "cpu":
        return Context("cpu", 0)
    return Context("tpu", 0)


def cpu(device_id: int = 0) -> Context:
    """CPU context (reference: python/mxnet/context.py:? ``mx.cpu``)."""
    return Context("cpu", device_id)


def tpu(device_id: int = 0, mesh=None) -> Context:
    """TPU context — the capability the north star adds to the reference.

    ``mesh`` activates a device mesh for the process in the same call
    (``mx.tpu(mesh={'dp': 4, 'tp': 2})``): a dict builds one via
    ``parallel.make_mesh``, a ``jax.sharding.Mesh`` is used as-is.
    Parameters initialized afterwards are born replicated over it, and
    ``Trainer(..., partition_rules=...)`` / ``parallel.shard_batch``
    pick it up without further wiring."""
    if mesh is not None:
        from . import parallel  # deferred: parallel imports context

        if isinstance(mesh, dict):
            mesh = parallel.make_mesh(mesh)
        parallel.set_mesh(mesh)
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compatibility alias so reference scripts run unchanged: resolves to the
    jax accelerator backend (TPU here), not an actual CUDA device."""
    return Context("gpu", device_id)


def current_context() -> Context:
    return Context.default_ctx()


def num_devices(device_type: Optional[str] = None) -> int:
    """Reference analog: ``mx.context.num_gpus()`` — counts THIS
    process's devices (like CUDA device enumeration), so the canonical
    ``[mx.tpu(i) for i in range(num_devices())]`` idiom stays valid in
    multi-process groups.  Use ``global_num_devices`` for mesh math."""
    import jax

    if device_type == "cpu":
        return len(jax.local_devices(backend="cpu"))
    return len(jax.local_devices())


def global_num_devices() -> int:
    """Total devices across the process group (``jax.device_count()``)."""
    import jax

    return jax.device_count()


def num_gpus() -> int:  # compat shim; counts accelerator devices
    return num_devices()


def num_tpus() -> int:
    return num_devices()
