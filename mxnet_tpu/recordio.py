"""RecordIO: the reference's packed-record container format.

Reference: ``3rdparty/dmlc-core/include/dmlc/recordio.h:?`` (binary layout)
+ ``python/mxnet/recordio.py:?`` (MXRecordIO/MXIndexedRecordIO/IRHeader).
Byte-compatible with files produced by the reference's ``im2rec`` tooling:

    [kMagic:u32][cflag|length:u32][payload][pad to 4B]   per record

where the upper 3 bits of the second word encode the continuation flag for
records split over 2^29-byte chunks.  The indexed variant keeps a text
``.idx`` (key \\t offset per line).  IRHeader packs (flag, label, id, id2)
ahead of image payloads.

TPU note: record *decode* stays on host (this module + cv2/PIL); arrays hit
the device via the DataLoader's sharded device_put (SURVEY §2.5).
"""
from __future__ import annotations

import numbers
import os
import struct

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_KMAGIC = 0xCED7230A
_LFLAG_BITS = 29
_MAX_CHUNK = (1 << _LFLAG_BITS) - 1


def _encode_lrec(cflag, length):
    return (cflag << _LFLAG_BITS) | length


def _decode_lrec(lrec):
    return lrec >> _LFLAG_BITS, lrec & _MAX_CHUNK


class MXRecordIO:
    """Sequential record reader/writer (reference ``mx.recordio.MXRecordIO``,
    dmlc RecordIOWriter/Reader semantics)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.fh = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.fh = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fh = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag!r}")
        self.is_open = True

    def close(self):
        if self.is_open and self.fh is not None:
            self.fh.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d.pop("fh", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.fh = None
        is_open = d.get("is_open", False)
        self.is_open = False
        if is_open:
            if self.flag == "w":
                # reopen for append-like continuation
                self.fh = open(self.uri, "ab")
                self.is_open = True
            else:
                self.open()

    def write(self, buf):
        if not self.writable:
            raise MXNetError("record file opened read-only")
        if not isinstance(buf, (bytes, bytearray)):
            raise MXNetError("write() takes bytes")
        data = bytes(buf)
        remaining = len(data)
        offset = 0
        first = True
        while remaining > 0 or first:
            chunk = min(remaining, _MAX_CHUNK)
            total_left = remaining - chunk
            if first:
                cflag = 0 if total_left == 0 else 1
            else:
                cflag = 3 if total_left == 0 else 2
            self.fh.write(struct.pack("<II", _KMAGIC,
                                      _encode_lrec(cflag, chunk)))
            self.fh.write(data[offset:offset + chunk])
            pad = (4 - chunk % 4) % 4
            if pad:
                self.fh.write(b"\x00" * pad)
            offset += chunk
            remaining -= chunk
            first = False

    def read(self):
        if self.writable:
            raise MXNetError("record file opened write-only")
        parts = []
        while True:
            header = self.fh.read(8)
            if len(header) < 8:
                return None if not parts else b"".join(parts)
            magic, lrec = struct.unpack("<II", header)
            if magic != _KMAGIC:
                raise MXNetError(
                    f"corrupt record file {self.uri!r}: bad magic")
            cflag, length = _decode_lrec(lrec)
            payload = self.fh.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.fh.read(pad)
            parts.append(payload)
            if cflag in (0, 3):
                return b"".join(parts)

    def tell(self):
        return self.fh.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file via a ``.idx`` sidecar (reference
    ``MXIndexedRecordIO`` — the ImageRecordIter's shard-seek mechanism)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as fin:
                for lineno, line in enumerate(fin, 1):
                    stripped = line.strip()
                    if not stripped:
                        # tolerate trailing newline / blank lines — im2rec
                        # and hand-edited indexes both produce them
                        continue
                    parts = stripped.split("\t")
                    try:
                        key = key_type(parts[0])
                        offset = int(parts[1])
                    except (IndexError, ValueError) as exc:
                        raise MXNetError(
                            f"corrupt index line {lineno} in "
                            f"{idx_path!r}: {stripped!r}") from exc
                    self.idx[key] = offset
                    self.keys.append(key)

    def close(self):
        if self.writable and self.is_open:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        if self.writable:
            raise MXNetError("cannot seek a writable indexed record file")
        self.fh.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.fh.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


class IRHeader:
    """Image-record header (reference struct: flag, label, id, id2)."""

    __slots__ = ("flag", "label", "id", "id2")
    _FMT = "<IfQQ"

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2

    def __iter__(self):
        return iter((self.flag, self.label, self.id, self.id2))

    def __eq__(self, other):
        return tuple(self) == tuple(other)


def pack(header, s):
    """Pack (IRHeader, payload bytes) (reference ``mx.recordio.pack``)."""
    header = IRHeader(*header) if not isinstance(header, IRHeader) else header
    label = header.label
    if isinstance(label, numbers.Number):
        packed = struct.pack(IRHeader._FMT, 0, float(label), header.id,
                             header.id2)
    else:
        label = np.asarray(label, dtype=np.float32)
        packed = struct.pack(IRHeader._FMT, len(label), 0.0, header.id,
                             header.id2) + label.tobytes()
    return packed + s


def unpack(s):
    """Unpack bytes → (IRHeader, payload) (reference ``unpack``)."""
    flag, label, id_, id2 = struct.unpack(
        IRHeader._FMT, s[:struct.calcsize(IRHeader._FMT)])
    s = s[struct.calcsize(IRHeader._FMT):]
    if flag > 0:
        label = np.frombuffer(s[:flag * 4], dtype=np.float32)
        s = s[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array and pack it (reference ``pack_img``)."""
    import cv2

    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        encode_params = None
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    if not ret:
        raise MXNetError(f"failed to encode image as {img_fmt}")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """Unpack and decode an image record (reference ``unpack_img``)."""
    import cv2

    header, img_bytes = unpack(s)
    img = cv2.imdecode(np.frombuffer(img_bytes, dtype=np.uint8), iscolor)
    return header, img
