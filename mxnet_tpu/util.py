"""Utility flags & helpers.

Reference: ``python/mxnet/util.py:?`` — the numpy-semantics switches
(``set_np``/``is_np_array``/``is_np_shape`` and the ``use_np*``
decorators, ≥1.6), ``getenv``/``setenv``, ``makedirs`` (SURVEY §2.4 misc
row).  These flags gate the ``mx.np`` front end exactly as in the
reference: classic mode keeps MXNet 1.x semantics (no zero-dim/zero-size
arrays), np mode enables NumPy-compatible shapes and array types.
"""
from __future__ import annotations

import functools
import os
import threading

from .base import MXNetError

_np_state = threading.local()


def _flags():
    if not hasattr(_np_state, "shape"):
        _np_state.shape = False
        _np_state.array = False
        _np_state.default_dtype = False
    return _np_state


def set_np_shape(active):
    """Enable zero-dim/zero-size shape semantics (reference
    ``mx.util.set_np_shape``).  Returns the previous state."""
    st = _flags()
    prev = st.shape
    st.shape = bool(active)
    return prev


def is_np_shape():
    return _flags().shape


def set_np_array(active):
    st = _flags()
    prev = st.array
    st.array = bool(active)
    return prev


def is_np_array():
    return _flags().array


def set_np(shape=True, array=True, dtype=False):
    """Activate NumPy semantics for shapes + arrays (reference
    ``mx.util.set_np`` / ``mx.npx.set_np``)."""
    if array and not shape:
        raise MXNetError("np array semantics require np shape semantics")
    set_np_shape(shape)
    set_np_array(array)
    _flags().default_dtype = bool(dtype)


def reset_np():
    """Back to classic MXNet semantics (reference ``mx.util.reset_np``)."""
    set_np(shape=False, array=False, dtype=False)


def set_np_default_dtype(is_np_default_dtype=True):
    st = _flags()
    prev = st.default_dtype
    st.default_dtype = bool(is_np_default_dtype)
    return prev


def is_np_default_dtype():
    return _flags().default_dtype


class np_shape:
    """Context manager/decorator scoping np-shape semantics (reference
    ``mx.util.np_shape``)."""

    def __init__(self, active=True):
        self._active = active
        self._prev = None

    def __enter__(self):
        self._prev = set_np_shape(self._active)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev)

    def __call__(self, func):
        @functools.wraps(func)
        def wrapped(*args, **kwargs):
            with np_shape(self._active):
                return func(*args, **kwargs)
        return wrapped


class np_array:
    """Context manager/decorator scoping np-array semantics (reference
    ``mx.util.np_array``)."""

    def __init__(self, active=True):
        self._active = active
        self._prev = None

    def __enter__(self):
        self._prev = set_np_array(self._active)
        return self

    def __exit__(self, *exc):
        set_np_array(self._prev)

    def __call__(self, func):
        @functools.wraps(func)
        def wrapped(*args, **kwargs):
            with np_array(self._active):
                return func(*args, **kwargs)
        return wrapped


def use_np_shape(func):
    return np_shape(True)(func)


def use_np_array(func):
    return np_array(True)(func)


def use_np(func):
    """Decorator activating full np semantics inside ``func`` (reference
    ``mx.util.use_np``)."""
    return use_np_shape(use_np_array(func))


def getenv(name):
    """Reference ``mx.util.getenv`` (dmlc GetEnv surface)."""
    return os.environ.get(name)


def setenv(name, value):
    os.environ[name] = str(value)


def makedirs(d):
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def get_gpu_count():
    return 0


def get_gpu_memory(dev_id=0):
    raise MXNetError("no CUDA GPUs in a TPU build")


def default_array(source_array, ctx=None, dtype=None):
    """Create an ndarray in the currently-active semantics (np or classic;
    reference ``mx.util.default_array``)."""
    if is_np_array():
        from . import numpy as _mx_np

        return _mx_np.array(source_array, ctx=ctx, dtype=dtype)
    from . import ndarray as nd

    return nd.array(source_array, ctx=ctx, dtype=dtype)
