"""Legacy ``mx.model`` namespace: FeedForward + checkpoint helpers.

Reference: ``python/mxnet/model.py:?`` (SURVEY §2.4 misc row) — the
pre-Module training API kept for backward compat; delegates to the same
executor machinery.  Here FeedForward wraps ``mx.mod.Module`` (itself
over the native Symbol executor), so one implementation serves all three
API generations (model → module → gluon).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .callback import BatchEndParam  # noqa: F401  (reference re-export)
from .serialization import load_checkpoint, save_checkpoint  # noqa: F401

__all__ = ["FeedForward", "BatchEndParam", "save_checkpoint",
           "load_checkpoint"]


class FeedForward:
    """Reference ``mx.model.FeedForward``: symbol + fit/predict."""

    def __init__(self, symbol, ctx=None, num_epoch=None, optimizer="sgd",
                 initializer=None, arg_params=None, aux_params=None,
                 begin_epoch=0, **optimizer_params):
        from . import context as ctx_mod

        self.symbol = symbol
        self.ctx = ctx or ctx_mod.current_context()
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.optimizer_params = optimizer_params or {}
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self._module = None

    def _create_module(self, data_iter):
        from . import module as mod

        label_descs = data_iter.provide_label or []
        m = mod.Module(self.symbol,
                       data_names=[d.name for d in data_iter.provide_data],
                       label_names=[l.name for l in label_descs],
                       context=self.ctx)
        self._module = m
        return m

    def _ensure_bound(self, data_iter):
        """Bind + init from stored params (the load-then-infer path)."""
        from . import initializer as init_mod

        if self._module is not None and self._module.binded:
            return self._module
        m = self._module or self._create_module(data_iter)
        m.bind(data_shapes=data_iter.provide_data,
               label_shapes=data_iter.provide_label or None,
               for_training=False)
        m.init_params(self.initializer or init_mod.Uniform(0.01),
                      arg_params=self.arg_params,
                      aux_params=self.aux_params,
                      allow_missing=self.arg_params is not None)
        return m

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            batch_end_callback=None, epoch_end_callback=None,
            logger=None, **kwargs):
        """Reference ``FeedForward.fit``: X is a DataIter (or arrays);
        delegates to the Module fit loop (one implementation serves all
        API generations)."""
        from . import io

        if self.num_epoch is None:
            raise MXNetError("num_epoch is required for fit")
        if hasattr(X, "provide_data"):
            data_iter = X
        else:
            if y is None:
                raise MXNetError("y must be specified when X is an array")
            data_iter = io.NDArrayIter(np.asarray(X), np.asarray(y),
                                       batch_size=32)
        m = self._create_module(data_iter)
        m.fit(data_iter, eval_data=eval_data, eval_metric=eval_metric,
              optimizer=self.optimizer,
              optimizer_params=dict(self.optimizer_params),
              initializer=self.initializer,
              arg_params=self.arg_params, aux_params=self.aux_params,
              allow_missing=self.arg_params is not None,
              begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
              batch_end_callback=batch_end_callback,
              epoch_end_callback=epoch_end_callback)
        self.arg_params, self.aux_params = m.get_params()
        return self

    def predict(self, X, num_batch=None):
        from . import io

        data_iter = X if hasattr(X, "provide_data") else \
            io.NDArrayIter(np.asarray(X), batch_size=32)
        m = self._ensure_bound(data_iter)
        outs = []
        data_iter.reset()
        for i, batch in enumerate(data_iter):
            if num_batch is not None and i >= num_batch:
                break
            m.forward(batch, is_train=False)
            out = m.get_outputs()[0].asnumpy()
            if batch.pad:
                out = out[:out.shape[0] - batch.pad]
            outs.append(out)
        return np.concatenate(outs, axis=0)

    def score(self, X, eval_metric="acc"):
        from . import metric as metric_mod

        m = self._ensure_bound(X)
        em = metric_mod.create(eval_metric)
        X.reset()
        for batch in X:
            m.forward(batch, is_train=False)
            m.update_metric(em, batch.label)
        return em.get()[1]

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None
                        else (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(sym, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @classmethod
    def create(cls, symbol, X, y=None, ctx=None, num_epoch=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", **kwargs):
        """Reference ``FeedForward.create``: construct AND train."""
        model = cls(symbol, ctx=ctx, num_epoch=num_epoch,
                    optimizer=optimizer, initializer=initializer,
                    **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric)
        return model
