"""ONNX ModelProto → Symbol graph importer (onnx2mx).

Reference: ``python/mxnet/contrib/onnx/onnx2mx/import_model.py:?`` +
``import_onnx.py:?`` (SURVEY §2.4) — walks GraphProto nodes, translating
each ONNX op to symbol calls and initializers to arg/aux params.  The
reference depends on the ``onnx`` python package; here the bundled
wire-format decoder (``_proto.parse``) reads ModelProto directly, so
import works with no external dependency — mirroring the exporter.

Supported op set = the exporter's (CNN/MLP: Conv, Gemm, BatchNorm, pools,
activations, Softmax/LogSoftmax, Concat, Flatten, Reshape, elementwise,
Dropout/Identity) — enough for round-trip plus simple external models.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import _proto as P

__all__ = ["import_model"]


# --- proto readers ----------------------------------------------------------

def _s64(v):
    """Protobuf int64 varints are two's-complement; sign-extend."""
    v = int(v)
    return v - (1 << 64) if v >= (1 << 63) else v


def _ints(parsed, number):
    """Repeated int field: both packed (wire 2) and unpacked (wire 0)."""
    out = []
    for f, w, v in parsed:
        if f != number:
            continue
        if w == 0:
            out.append(_s64(v))
        elif w == 2:
            i = 0
            while i < len(v):
                x, i = P._read_varint(v, i)
                out.append(_s64(x))
    return out


_DT2NP = {P.FLOAT: np.float32, P.DOUBLE: np.float64, P.INT64: np.int64,
          P.INT32: np.int32, P.INT8: np.int8, P.UINT8: np.uint8,
          P.FLOAT16: np.float16, P.BOOL: np.bool_}


def _read_tensor(buf):
    """TensorProto → (name, np.ndarray)."""
    parsed = P.parse(buf)
    dims = _ints(parsed, 1)
    (dtype,) = _ints(parsed, 2) or [P.FLOAT]
    name = b"".join(P.fields(parsed, 8)).decode("utf-8")
    np_dt = _DT2NP.get(dtype)
    if np_dt is None:
        raise MXNetError(f"ONNX import: unsupported tensor dtype {dtype}")
    raw = b"".join(P.fields(parsed, 9))
    if raw:
        arr = np.frombuffer(raw, dtype=np_dt).reshape(dims)
    elif dtype == P.FLOAT:
        vals = []
        for f, w, v in parsed:
            if f != 4:
                continue
            data = v if w == 2 else np.uint32(v).tobytes()
            vals.append(np.frombuffer(data, dtype=np.float32))
        arr = (np.concatenate(vals) if vals
               else np.zeros(0, np.float32)).reshape(dims)
    elif dtype == P.INT64:
        arr = np.asarray(_ints(parsed, 7), np.int64).reshape(dims)
    elif dtype in (P.INT32, P.INT8, P.UINT8, P.BOOL):
        arr = np.asarray(_ints(parsed, 5), np.int64).astype(np_dt) \
            .reshape(dims)
    else:
        raise MXNetError(
            f"ONNX import: tensor {name!r} has no raw_data and dtype "
            f"{dtype} typed-data decoding is not supported")
    return name, np.array(arr)  # copy: frombuffer views are read-only


def _read_value_info(buf):
    """ValueInfoProto → (name, shape-or-None)."""
    parsed = P.parse(buf)
    name = b"".join(P.fields(parsed, 1)).decode("utf-8")
    shape = None
    types = P.fields(parsed, 2)
    if types:
        tparsed = P.parse(types[0])
        tens = P.fields(tparsed, 1)  # TypeProto.tensor_type
        if tens:
            tt = P.parse(tens[0])
            shapes = P.fields(tt, 2)
            if shapes:
                dims = []
                for dbuf in P.fields(P.parse(shapes[0]), 1):
                    dv = _ints(P.parse(dbuf), 1)
                    dims.append(int(dv[0]) if dv else 0)
                shape = tuple(dims)
    return name, shape


def _read_attr(buf):
    """AttributeProto → (name, python value)."""
    parsed = P.parse(buf)
    name = b"".join(P.fields(parsed, 1)).decode("utf-8")
    atype = (_ints(parsed, 20) or [0])[0]
    if atype == P.ATTR_FLOAT:
        import struct

        (v,) = P.fields(parsed, 2) or [0]
        return name, struct.unpack("<f", np.uint32(v).tobytes())[0]
    if atype == P.ATTR_INT:
        return name, (_ints(parsed, 3) or [0])[0]
    if atype == P.ATTR_STRING:
        return name, b"".join(P.fields(parsed, 4)).decode("utf-8")
    if atype == P.ATTR_INTS:
        return name, _ints(parsed, 8)
    if atype == P.ATTR_TENSOR:
        ts = P.fields(parsed, 5)
        return name, _read_tensor(ts[0])[1] if ts else None
    if atype == P.ATTR_FLOATS:
        vals = []
        for f, w, v in parsed:
            if f == 7:
                data = v if w == 2 else np.uint32(v).tobytes()
                vals.append(np.frombuffer(data, dtype=np.float32))
        return name, list(np.concatenate(vals)) if vals else []
    return name, None


def _read_node(buf):
    parsed = P.parse(buf)
    return {
        "inputs": [b.decode("utf-8") for b in P.fields(parsed, 1)],
        "outputs": [b.decode("utf-8") for b in P.fields(parsed, 2)],
        "name": b"".join(P.fields(parsed, 3)).decode("utf-8"),
        "op_type": b"".join(P.fields(parsed, 4)).decode("utf-8"),
        "attrs": dict(_read_attr(a) for a in P.fields(parsed, 5)),
    }


# --- op translations (ONNX → symbol calls) ----------------------------------

def _sym_pads(attrs, what):
    pads = attrs.get("pads")
    if not pads:
        return None
    n = len(pads) // 2
    begin, end = tuple(pads[:n]), tuple(pads[n:])
    if begin != end:
        raise MXNetError(
            f"ONNX import: asymmetric pads {pads} on {what} not supported")
    return begin


def _conv(sym, node, ins, params):
    w = params.get(node["inputs"][1])
    if w is None:
        raise MXNetError("ONNX import: Conv weight must be an initializer")
    a = node["attrs"]
    kw = dict(kernel=tuple(a.get("kernel_shape", w.shape[2:])),
              num_filter=int(w.shape[0]),
              num_group=int(a.get("group", 1)),
              no_bias=len(ins) < 3)
    if a.get("strides"):
        kw["stride"] = tuple(a["strides"])
    if a.get("dilations"):
        kw["dilate"] = tuple(a["dilations"])
    pad = _sym_pads(a, "Conv")
    if pad:
        kw["pad"] = pad
    return sym.Convolution(*ins[:3], name=node["outputs"][0], **kw)


def _gemm(sym, node, ins, params):
    a = node["attrs"]
    if float(a.get("alpha", 1.0)) != 1.0:
        raise MXNetError("ONNX import: Gemm alpha != 1 unsupported")
    if int(a.get("transA", 0)):
        raise MXNetError("ONNX import: Gemm transA=1 unsupported")
    w = params.get(node["inputs"][1])
    if w is None:
        raise MXNetError("ONNX import: Gemm weight must be an initializer")
    if not int(a.get("transB", 0)):
        params[node["inputs"][1]] = w = np.ascontiguousarray(w.T)
    beta = float(a.get("beta", 1.0))
    use_bias = len(ins) >= 3 and beta != 0.0
    if use_bias and beta != 1.0:
        raise MXNetError("ONNX import: Gemm beta not in (0, 1) unsupported")
    return sym.FullyConnected(*ins[:3 if use_bias else 2],
                              num_hidden=int(w.shape[0]),
                              flatten=False, no_bias=not use_bias,
                              name=node["outputs"][0])


def _pool(pool_type, global_pool=False):
    def f(sym, node, ins, params):
        a = node["attrs"]
        kw = dict(pool_type=pool_type, name=node["outputs"][0])
        if global_pool:
            kw["global_pool"] = True
            kw["kernel"] = (1, 1)
        else:
            kw["kernel"] = tuple(a["kernel_shape"])
            if a.get("strides"):
                kw["stride"] = tuple(a["strides"])
            pad = _sym_pads(a, "Pool")
            if pad:
                kw["pad"] = pad
        return sym.Pooling(ins[0], **kw)
    return f


def _bn(sym, node, ins, params):
    a = node["attrs"]
    return sym.BatchNorm(*ins[:5], eps=float(a.get("epsilon", 1e-5)),
                         momentum=float(a.get("momentum", 0.9)),
                         fix_gamma=False, name=node["outputs"][0])


def _act(op):
    def f(sym, node, ins, params):
        return getattr(sym, op)(ins[0], name=node["outputs"][0])
    return f


def _softmax(op):
    def f(sym, node, ins, params):
        axis = int(node["attrs"].get("axis", -1))
        return getattr(sym, op)(ins[0], axis=axis, name=node["outputs"][0])
    return f


def _binop(op):
    def f(sym, node, ins, params):
        return getattr(sym, op)(ins[0], ins[1], name=node["outputs"][0])
    return f


def _concat(sym, node, ins, params):
    return sym.concat(*ins, dim=int(node["attrs"].get("axis", 1)),
                      name=node["outputs"][0])


def _flatten(sym, node, ins, params):
    if int(node["attrs"].get("axis", 1)) != 1:
        raise MXNetError("ONNX import: Flatten axis != 1 unsupported")
    return sym.Flatten(ins[0], name=node["outputs"][0])


def _reshape(sym, node, ins, params):
    shape = params.get(node["inputs"][1])
    if shape is None:
        raise MXNetError(
            "ONNX import: Reshape shape must be an initializer")
    return sym.Reshape(ins[0], shape=tuple(int(s) for s in shape),
                       name=node["outputs"][0])


def _identity(sym, node, ins, params):
    return sym.identity(ins[0], name=node["outputs"][0])


# --- NLP subset (round 4) ----------------------------------------------------

_ONNX2DT = {P.FLOAT: "float32", P.INT64: "int64", 6: "int32",
            10: "float16", 11: "float64"}  # BOOL handled in _cast


def _matmul(sym, node, ins, params):
    # transformer use is batched rank>=3; batch_dot broadcasts leading
    # dims (2-D standalone MatMul exports arrive as Gemm instead)
    return sym.batch_dot(ins[0], ins[1], name=node["outputs"][0])


def _transpose_imp(sym, node, ins, params):
    perm = node["attrs"].get("perm")
    kw = {} if perm is None else {"axes": tuple(int(p) for p in perm)}
    return sym.transpose(ins[0], name=node["outputs"][0], **kw)


def _gather(sym, node, ins, params):
    axis = int(node["attrs"].get("axis", 0))
    return sym.take(ins[0], ins[1], axis=axis,
                    name=node["outputs"][0])


def _cast(sym, node, ins, params):
    to = int(node["attrs"].get("to", P.FLOAT))
    if to == P.BOOL:
        # bool semantics = (x != 0) collapsed to 0/1, NOT a
        # value-preserving cast (a later Cast-to-float of a real bool
        # yields 1.0, never the original magnitude)
        return sym.sign(sym.abs(ins[0]), name=node["outputs"][0])
    dt = _ONNX2DT.get(to)
    if dt is None:
        raise MXNetError(f"ONNX import: Cast to={to} unsupported")
    return sym.cast(ins[0], dtype=dt, name=node["outputs"][0])


def _leaky(sym, node, ins, params):
    return sym.LeakyReLU(ins[0],
                         slope=float(node["attrs"].get("alpha", 0.01)),
                         name=node["outputs"][0])


def _elu(sym, node, ins, params):
    return sym.LeakyReLU(ins[0], act_type="elu",
                         slope=float(node["attrs"].get("alpha", 1.0)),
                         name=node["outputs"][0])


def _reduce_mean(sym, node, ins, params):
    axes = node["attrs"].get("axes")
    kw = {"keepdims": bool(int(node["attrs"].get("keepdims", 1)))}
    if axes is not None:
        kw["axis"] = tuple(int(a) for a in axes)
    return sym.mean(ins[0], name=node["outputs"][0], **kw)


def _slice_imp(sym, node, ins, params):
    def arr(i):
        v = params.get(node["inputs"][i])
        if v is None:
            raise MXNetError(
                "ONNX import: Slice indices must be initializers")
        return [int(x) for x in np.asarray(v).ravel()]

    starts, ends = arr(1), arr(2)
    axes = arr(3) if len(node["inputs"]) > 3 else \
        list(range(len(starts)))
    if len(node["inputs"]) > 4:
        steps = arr(4)
        if any(s != 1 for s in steps):
            raise MXNetError(
                f"ONNX import: strided Slice (steps={steps}) "
                "unsupported (subset)")
    if len(starts) != 1:
        raise MXNetError(
            "ONNX import: multi-axis Slice unsupported (subset)")
    end = None if ends[0] >= 2 ** 31 else ends[0]
    return sym.slice_axis(ins[0], axis=axes[0], begin=starts[0],
                          end=end, name=node["outputs"][0])


def _unsqueeze(sym, node, ins, params):
    axes = params.get(node["inputs"][1])
    if axes is None:
        raise MXNetError(
            "ONNX import: Unsqueeze axes must be an initializer")
    axes = [int(a) for a in np.asarray(axes).ravel()]
    if len(axes) != 1:
        raise MXNetError("ONNX import: multi-axis Unsqueeze unsupported")
    return sym.expand_dims(ins[0], axis=axes[0],
                           name=node["outputs"][0])


def _where_imp(sym, node, ins, params):
    return sym.where(ins[0], ins[1], ins[2], name=node["outputs"][0])


def _clip_imp(sym, node, ins, params):
    def scalar(i):
        if len(node["inputs"]) <= i or not node["inputs"][i]:
            return None  # genuinely absent optional bound
        v = params.get(node["inputs"][i])
        if v is None:
            raise MXNetError(
                "ONNX import: Clip bounds must be initializers "
                "(computed min/max unsupported in the subset)")
        return float(np.asarray(v).ravel()[0])

    lo, hi = scalar(1), scalar(2)
    if lo is None and hi is None:
        return sym.identity(ins[0], name=node["outputs"][0])
    # absent single bound: exact float32 extreme, so legitimate values up
    # to f32 max pass through unclipped
    f32max = float(np.finfo(np.float32).max)
    return sym.clip(ins[0],
                    a_min=-f32max if lo is None else lo,
                    a_max=f32max if hi is None else hi,
                    name=node["outputs"][0])


# inputs consumed as attributes (constants) per op: {op: input indices}
_ATTR_ONLY_INPUTS = {"Reshape": (1,), "Slice": (1, 2, 3, 4),
                     "Unsqueeze": (1,), "Clip": (1, 2)}


_IMPORTS = {
    "Conv": _conv,
    "Gemm": _gemm,
    "BatchNormalization": _bn,
    "MaxPool": _pool("max"),
    "AveragePool": _pool("avg"),
    "GlobalMaxPool": _pool("max", global_pool=True),
    "GlobalAveragePool": _pool("avg", global_pool=True),
    "Relu": _act("relu"),
    "Sigmoid": _act("sigmoid"),
    "Tanh": _act("tanh"),
    "Exp": _act("exp"),
    "Log": _act("log"),
    "Sqrt": _act("sqrt"),
    "Softplus": _act("softrelu"),
    "Softmax": _softmax("softmax"),
    "LogSoftmax": _softmax("log_softmax"),
    "Concat": _concat,
    "Flatten": _flatten,
    "Reshape": _reshape,
    "Dropout": _identity,
    "Identity": _identity,
    "Add": _binop("broadcast_add"),
    "Mul": _binop("broadcast_mul"),
    "Sub": _binop("broadcast_sub"),
    "Div": _binop("broadcast_div"),
    # NLP subset (round 4)
    "MatMul": _matmul,
    "Transpose": _transpose_imp,
    "Gather": _gather,
    "Cast": _cast,
    "Erf": _act("erf"),
    "LeakyRelu": _leaky,
    "Elu": _elu,
    "ReduceMean": _reduce_mean,
    "Slice": _slice_imp,
    "Unsqueeze": _unsqueeze,
    "Where": _where_imp,
    "Pow": _binop("broadcast_power"),
    "Max": _binop("broadcast_maximum"),
    "Min": _binop("broadcast_minimum"),
    "Clip": _clip_imp,
}


def import_model(model_file):
    """Reference ``mx.contrib.onnx.import_model``: ONNX file →
    ``(sym, arg_params, aux_params)``."""
    from ... import ndarray as nd
    from ... import symbol as sym_mod

    with open(model_file, "rb") as f:
        model = P.parse(f.read())
    graphs = P.fields(model, 7)
    if not graphs:
        raise MXNetError(f"{model_file!r} has no GraphProto")
    g = P.parse(graphs[0])

    params = {}
    for t in P.fields(g, 5):
        name, arr = _read_tensor(t)
        params[name] = arr
    inputs = [_read_value_info(v) for v in P.fields(g, 11)]
    outputs = [_read_value_info(v) for v in P.fields(g, 12)]
    nodes = [_read_node(n) for n in P.fields(g, 1)]

    tensors = {}
    for name, _shape in inputs:
        if name not in params:
            tensors[name] = sym_mod.Variable(name)
    aux_names = set()
    for node in nodes:
        op = node["op_type"]
        if op == "Constant":
            # value feeds downstream as an initializer-like tensor
            val = node["attrs"].get("value")
            if val is None:
                raise MXNetError("ONNX import: Constant without value")
            params[node["outputs"][0]] = np.asarray(val)
            continue
        trans = _IMPORTS.get(op)
        if trans is None:
            raise MXNetError(
                f"ONNX import: op {op!r} has no translation "
                f"(supported: {sorted(_IMPORTS)})")
        if op == "BatchNormalization":
            aux_names.update(node["inputs"][3:5])
        ins = []
        # consumed-as-attribute inputs (Reshape shape, Slice/Unsqueeze
        # indices) stay out of the symbol graph
        attr_only = {node["inputs"][i]
                     for i in _ATTR_ONLY_INPUTS.get(op, ())
                     if i < len(node["inputs"])}
        for iname in node["inputs"]:
            if iname in attr_only:
                continue
            if iname not in tensors:
                if iname in params:
                    tensors[iname] = sym_mod.Variable(iname)
                else:
                    raise MXNetError(
                        f"ONNX import: undefined tensor {iname!r}")
            ins.append(tensors[iname])
        result = trans(sym_mod, node, ins, params)
        outs = result if isinstance(result, (list, tuple)) else [result]
        for oname, o in zip(node["outputs"], outs):
            tensors[oname] = o

    heads = []
    for name, _shape in outputs:
        if name not in tensors:
            if name in params:
                # graph output refers straight to an initializer
                # (Identity-folded models): surface it as a bound variable
                tensors[name] = sym_mod.Variable(name)
            else:
                raise MXNetError(
                    f"ONNX import: graph output {name!r} refers to an "
                    "undefined tensor")
        heads.append(tensors[name])
    sym = heads[0] if len(heads) == 1 else sym_mod.Group(heads)
    # only tensors that actually became graph Variables are parameters:
    # attribute-consumed inputs (Reshape shapes, Slice/Clip bounds) and
    # folded Constants must NOT surface as bindable params — they'd trip
    # Module.set_params(allow_extra=False) as unexpected keys
    used = set(sym.list_arguments()) | set(
        sym.list_auxiliary_states())
    arg_params = {k: nd.array(np.asarray(v)) for k, v in params.items()
                  if k in used and k not in aux_names}
    aux_params = {k: nd.array(np.asarray(params[k])) for k in aux_names
                  if k in params}
    return sym, arg_params, aux_params
