"""Minimal protobuf wire-format encoder/decoder for ONNX.

Reference: ``python/mxnet/contrib/onnx/mx2onnx/`` (SURVEY §2.4 onnx row)
builds ModelProto via the ``onnx`` python package; that package is not in
this image, so the exporter emits the protobuf wire format directly.
Field numbers follow onnx.proto (stable across ONNX releases; IR version
pinned below).  The decoder exists for round-trip tests and the importer.

Wire format: each field = varint key (field_number << 3 | wire_type) +
payload.  Wire types used: 0 = varint, 2 = length-delimited, 5 = 32-bit.
"""
from __future__ import annotations

import struct

# onnx TensorProto.DataType
FLOAT = 1
UINT8 = 2
INT8 = 3
INT32 = 6
INT64 = 7
BOOL = 9
FLOAT16 = 10
DOUBLE = 11
BF16 = 16

# AttributeProto.AttributeType
ATTR_FLOAT = 1
ATTR_INT = 2
ATTR_STRING = 3
ATTR_TENSOR = 4
ATTR_FLOATS = 6
ATTR_INTS = 7
ATTR_STRINGS = 8


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def fint(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(int(value))


def fbytes(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def fstr(field: int, s: str) -> bytes:
    return fbytes(field, s.encode("utf-8"))


def ffloat(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", float(value))


def fpacked_ints(field: int, values) -> bytes:
    payload = b"".join(_varint(int(v)) for v in values)
    return fbytes(field, payload)


# --- decoder (for tests / importer) -----------------------------------------

def parse(buf: bytes):
    """→ list of (field_number, wire_type, value); value is int for
    varint/32-bit, bytes for length-delimited."""
    out = []
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<I", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            v = struct.unpack("<Q", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.append((field, wire, v))
    return out


def _read_varint(buf: bytes, i: int):
    shift = 0
    result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def fields(parsed, number):
    return [v for f, _w, v in parsed if f == number]
