"""``mx.contrib.onnx`` (reference ``python/mxnet/contrib/onnx/
__init__.py:?``): ONNX export (mx2onnx) AND import (onnx2mx), both over
the bundled protobuf wire-format codec — no ``onnx`` package dependency
in either direction (the reference needs it for both)."""
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import import_model  # noqa: F401
