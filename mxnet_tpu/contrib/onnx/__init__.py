"""``mx.contrib.onnx`` (reference ``python/mxnet/contrib/onnx/
__init__.py:?``): ONNX export (mx2onnx).  Import (onnx2mx) requires the
``onnx`` package to parse arbitrary external models and is gated on it;
models exported HERE round-trip through the bundled wire-format decoder
(see tests/test_onnx.py)."""
from .mx2onnx import export_model  # noqa: F401


def import_model(model_file):
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "onnx2mx import requires the 'onnx' package, which is not "
            "installed in this environment") from e
    raise NotImplementedError(
        "onnx2mx import lands when an onnx runtime is available")
