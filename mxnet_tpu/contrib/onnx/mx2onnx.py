"""Symbol graph → ONNX ModelProto exporter.

Reference: ``python/mxnet/contrib/onnx/mx2onnx/export_model.py:?`` +
``_op_translations.py:?`` (SURVEY §2.4) — per-op translation table from
the nnvm graph to ONNX nodes.  Here the walk runs over the native Symbol
node graph and the bytes are produced by the wire-format encoder in
``_proto.py`` (no ``onnx`` package dependency); opset 13.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import _proto as P

_OPSET = 13
_IR_VERSION = 8


def _tensor(name, arr):
    shape = np.shape(arr)
    # ascontiguousarray promotes 0-d to (1,) on NumPy 2.x — restore the
    # true rank (ONNX requires e.g. Clip bounds to be rank-0)
    arr = np.ascontiguousarray(arr).reshape(shape)
    dt = {np.dtype(np.float32): P.FLOAT, np.dtype(np.float64): P.DOUBLE,
          np.dtype(np.int64): P.INT64, np.dtype(np.int32): P.INT32,
          np.dtype(np.int8): P.INT8, np.dtype(np.uint8): P.UINT8,
          np.dtype(np.float16): P.FLOAT16}.get(arr.dtype)
    if dt is None:
        raise MXNetError(f"unsupported dtype {arr.dtype} for ONNX export")
    body = b"".join(P.fint(1, d) for d in arr.shape)
    body += P.fint(2, dt)
    body += P.fstr(8, name)
    body += P.fbytes(9, arr.tobytes())          # raw_data
    return body


def _value_info(name, shape, elem_type=P.FLOAT):
    dims = b"".join(P.fbytes(1, P.fint(1, int(d))) for d in shape)
    tensor_type = P.fint(1, elem_type) + P.fbytes(2, dims)
    type_proto = P.fbytes(1, tensor_type)
    return P.fstr(1, name) + P.fbytes(2, type_proto)


def _attr(name, value):
    body = P.fstr(1, name)
    if isinstance(value, float):
        body += P.ffloat(2, value) + P.fint(20, P.ATTR_FLOAT)
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        body += P.fint(3, int(value)) + P.fint(20, P.ATTR_INT)
    elif isinstance(value, str):
        body += P.fbytes(4, value.encode()) + P.fint(20, P.ATTR_STRING)
    elif isinstance(value, np.ndarray):
        body += P.fbytes(5, _tensor(name, value)) + \
            P.fint(20, P.ATTR_TENSOR)
    elif isinstance(value, (list, tuple)):
        body += P.fpacked_ints(8, value) + P.fint(20, P.ATTR_INTS)
    else:
        raise MXNetError(f"unsupported attribute {name}={value!r}")
    return body


def _const(out, arr):
    """Constant node carrying ``arr`` as its value tensor — used by the
    decomposed NLP exports (LayerNorm eps, GELU constants, Reshape
    shapes, Slice indices)."""
    return _node("Constant", [], [out], out,
                 {"value": np.asarray(arr)})


def _node(op_type, inputs, outputs, name, attrs=None):
    body = b"".join(P.fstr(1, i) for i in inputs)
    body += b"".join(P.fstr(2, o) for o in outputs)
    body += P.fstr(3, name)
    body += P.fstr(4, op_type)
    for k, v in (attrs or {}).items():
        body += P.fbytes(5, _attr(k, v))
    return body


def _tup(v, n, default):
    """Normalize kernel/stride/pad attrs to rank ``n`` (same defaults as
    the runtime ops: stride/dilate → 1, pad → 0)."""
    if v is None:
        return (default,) * n
    t = tuple(int(x) for x in (v if isinstance(v, (list, tuple)) else
                               (v,) * n))
    if len(t) != n:
        raise MXNetError(f"attribute rank {len(t)} != spatial rank {n}")
    return t


# --- per-op translations ----------------------------------------------------

def _kernel_attr(attrs, op):
    """Kernel rank drives every other spatial attr.  The runtime derives
    rank from the DATA shape for scalar/missing kernels; export has no
    shapes, so both cases need an explicit tuple — fail clearly."""
    k = attrs.get("kernel")
    if k is None or isinstance(k, (int, np.integer)):
        raise MXNetError(
            f"ONNX export: {op} needs an explicit kernel tuple, e.g. "
            f"kernel=(3, 3) (got {k!r}; the runtime infers spatial rank "
            "from data shapes, export cannot)")
    return tuple(int(x) for x in k)


def _conv(node, ins, out, attrs):
    kernel = _kernel_attr(attrs, "Convolution")
    n = len(kernel)
    stride = _tup(attrs.get("stride"), n, 1)
    pad = _tup(attrs.get("pad"), n, 0)
    dil = _tup(attrs.get("dilate"), n, 1)
    a = {"kernel_shape": kernel, "strides": stride,
         "pads": pad + pad, "dilations": dil,
         "group": int(attrs.get("num_group", 1))}
    return [_node("Conv", ins, [out], out, a)]


def _fc(node, ins, out, attrs):
    flatten = str(attrs.get("flatten", True)).lower() != "false"
    nodes = []
    data = ins[0]
    if flatten:
        nodes.append(_node("Flatten", [data], [out + "_flat"],
                           out + "_flatten", {"axis": 1}))
        data = out + "_flat"
    gemm_ins = [data, ins[1]] + ins[2:]
    nodes.append(_node("Gemm", gemm_ins, [out], out,
                       {"alpha": 1.0, "beta": 1.0, "transA": 0,
                        "transB": 1}))
    return nodes


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _activation(node, ins, out, attrs):
    act = attrs.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"ONNX export: unsupported act_type {act!r}")
    return [_node(_ACT[act], ins[:1], [out], out)]


def _bn(node, ins, out, attrs):
    # mxnet order: data gamma beta moving_mean moving_var (matches ONNX)
    return [_node("BatchNormalization", ins[:5], [out], out,
                  {"epsilon": float(attrs.get("eps", 1e-5)),
                   "momentum": float(attrs.get("momentum", 0.9))})]


def _pool(node, ins, out, attrs):
    ptype = attrs.get("pool_type", "max")
    if str(attrs.get("global_pool", False)).lower() in ("true", "1"):
        op = "GlobalAveragePool" if ptype == "avg" else "GlobalMaxPool"
        return [_node(op, ins[:1], [out], out)]
    kernel = _kernel_attr(attrs, "Pooling")
    n = len(kernel)
    stride = _tup(attrs.get("stride"), n, 1)
    pad = _tup(attrs.get("pad"), n, 0)
    op = "AveragePool" if ptype == "avg" else "MaxPool"
    return [_node(op, ins[:1], [out], out,
                  {"kernel_shape": kernel, "strides": stride,
                   "pads": pad + pad})]


def _simple(onnx_op, n_in=1):
    def conv(node, ins, out, attrs):
        return [_node(onnx_op, ins[:n_in], [out], out)]
    return conv


def _softmax(node, ins, out, attrs):
    return [_node("Softmax", ins[:1], [out], out,
                  {"axis": int(attrs.get("axis", -1))})]


def _concat(node, ins, out, attrs):
    return [_node("Concat", ins, [out], out,
                  {"axis": int(attrs.get("dim", 1))})]


def _dropout(node, ins, out, attrs):
    return [_node("Identity", ins[:1], [out], out)]  # inference export


def _elemwise(onnx_op):
    def conv(node, ins, out, attrs):
        return [_node(onnx_op, ins[:2], [out], out)]
    return conv


# --- NLP subset (round 4): LayerNorm/GELU/attention building blocks ---------

def _layer_norm(node, ins, out, attrs):
    """Opset-13 decomposition (LayerNormalization is opset 17):
    (x - mean) / sqrt(var + eps) * gamma + beta over the last axis."""
    axis = int(attrs.get("axis", -1))
    eps = float(attrs.get("eps", 1e-5))
    x, g, b = ins[0], ins[1], ins[2]

    def n(s):
        return f"{out}__{s}"

    return [
        _node("ReduceMean", [x], [n("mu")], n("mu"),
              {"axes": [axis], "keepdims": 1}),
        _node("Sub", [x, n("mu")], [n("d")], n("d")),
        _node("Mul", [n("d"), n("d")], [n("d2")], n("d2")),
        _node("ReduceMean", [n("d2")], [n("var")], n("var"),
              {"axes": [axis], "keepdims": 1}),
        _const(n("eps"), np.float32(eps)),
        _node("Add", [n("var"), n("eps")], [n("ve")], n("ve")),
        _node("Sqrt", [n("ve")], [n("std")], n("std")),
        _node("Div", [n("d"), n("std")], [n("norm")], n("norm")),
        _node("Mul", [n("norm"), g], [n("sc")], n("sc")),
        _node("Add", [n("sc"), b], [out], out),
    ]


def _leaky_relu(node, ins, out, attrs):
    act = attrs.get("act_type", "leaky")
    x = ins[0]

    def n(s):
        return f"{out}__{s}"

    if act == "leaky":
        return [_node("LeakyRelu", [x], [out], out,
                      {"alpha": float(attrs.get("slope", 0.25))})]
    if act == "elu":
        # runtime default slope is 0.25 (LeakyReLU family default), NOT
        # ONNX Elu's 1.0 — exporting the wrong default is 4x off on
        # every negative value
        return [_node("Elu", [x], [out], out,
                      {"alpha": float(attrs.get("slope", 0.25))})]
    if act == "gelu":
        # exact erf form: 0.5 x (1 + erf(x / sqrt(2)))
        return [
            _const(n("rsqrt2"), np.float32(1.0 / np.sqrt(2.0))),
            _node("Mul", [x, n("rsqrt2")], [n("xs")], n("xs")),
            _node("Erf", [n("xs")], [n("erf")], n("erf")),
            _const(n("one"), np.float32(1.0)),
            _node("Add", [n("erf"), n("one")], [n("e1")], n("e1")),
            _node("Mul", [x, n("e1")], [n("xe")], n("xe")),
            _const(n("half"), np.float32(0.5)),
            _node("Mul", [n("xe"), n("half")], [out], out),
        ]
    raise MXNetError(f"ONNX export: unsupported LeakyReLU act {act!r}")


def _embedding(node, ins, out, attrs):
    # mx Embedding(data, weight) -> Gather(weight, int64(data)).
    # The runtime CLIPS ids to [0, input_dim-1] (nn_ops.embedding);
    # a bare Gather instead wraps negatives from the end and errors on
    # overflow in external runtimes — export the clip explicitly.
    input_dim = attrs.get("input_dim")
    if input_dim is None:
        raise MXNetError(
            "ONNX export: Embedding needs input_dim to export the "
            "runtime's id-clipping semantics")

    def n(s):
        return f"{out}__{s}"

    return [
        _node("Cast", [ins[0]], [n("ids")], n("ids"), {"to": P.INT64}),
        _const(n("lo"), np.asarray(0, np.int64)),
        _const(n("hi"), np.asarray(int(input_dim) - 1, np.int64)),
        _node("Clip", [n("ids"), n("lo"), n("hi")], [n("cl")], n("cl")),
        _node("Gather", [ins[1], n("cl")], [out], out, {"axis": 0}),
    ]


def _batch_dot(node, ins, out, attrs):
    ta = str(attrs.get("transpose_a", False)).lower() in ("true", "1")
    tb = str(attrs.get("transpose_b", False)).lower() in ("true", "1")
    if ta or tb:
        raise MXNetError(
            "ONNX export: batch_dot transpose flags need the operand "
            "rank (unknown at export) — insert an explicit transpose "
            "before batch_dot instead")
    return [_node("MatMul", ins[:2], [out], out)]


def _transpose_exp(node, ins, out, attrs):
    axes = attrs.get("axes")
    a = {} if axes in (None, "None", ()) else \
        {"perm": [int(x) for x in axes]}
    return [_node("Transpose", ins[:1], [out], out, a)]


def _reshape_exp(node, ins, out, attrs):
    shape = tuple(int(s) for s in attrs.get("shape", ()))
    if any(s in (0, -2, -3, -4) for s in shape):
        raise MXNetError(
            "ONNX export: mx reshape special codes (0/-2/-3/-4) "
            f"unsupported, got {shape}")
    return [
        _const(out + "__shape", np.asarray(shape, np.int64)),
        _node("Reshape", [ins[0], out + "__shape"], [out], out),
    ]


def _slice_axis_exp(node, ins, out, attrs):
    axis = int(attrs.get("axis", 0))
    begin = int(attrs.get("begin", 0))
    end = attrs.get("end")
    end = 2 ** 62 if end in (None, "None") else int(end)
    return [
        _const(out + "__st", np.asarray([begin], np.int64)),
        _const(out + "__en", np.asarray([end], np.int64)),
        _const(out + "__ax", np.asarray([axis], np.int64)),
        _node("Slice", [ins[0], out + "__st", out + "__en",
                        out + "__ax"], [out], out),
    ]


def _expand_dims_exp(node, ins, out, attrs):
    return [
        _const(out + "__ax",
               np.asarray([int(attrs.get("axis", 0))], np.int64)),
        _node("Unsqueeze", [ins[0], out + "__ax"], [out], out),
    ]


def _where_exp(node, ins, out, attrs):
    return [
        _node("Cast", [ins[0]], [out + "__c"], out + "__c",
              {"to": P.BOOL}),
        _node("Where", [out + "__c", ins[1], ins[2]], [out], out),
    ]


_TRANSLATIONS = {
    "Convolution": _conv,
    "FullyConnected": _fc,
    "Activation": _activation,
    "BatchNorm": _bn,
    "batch_norm": _bn,
    "Pooling": _pool,
    "Flatten": lambda n, i, o, a: [_node("Flatten", i[:1], [o], o,
                                         {"axis": 1})],
    "softmax": _softmax,
    "log_softmax": lambda n, i, o, a: [_node("LogSoftmax", i[:1], [o], o)],
    "Concat": _concat,
    "concat": _concat,
    "Dropout": _dropout,
    "dropout": _dropout,
    "relu": _simple("Relu"),
    "sigmoid": _simple("Sigmoid"),
    "tanh": _simple("Tanh"),
    "exp": _simple("Exp"),
    "log": _simple("Log"),
    "sqrt": _simple("Sqrt"),
    "elemwise_add": _elemwise("Add"),
    "add": _elemwise("Add"),
    "broadcast_add": _elemwise("Add"),
    "elemwise_mul": _elemwise("Mul"),
    "mul": _elemwise("Mul"),
    "broadcast_mul": _elemwise("Mul"),
    "elemwise_sub": _elemwise("Sub"),
    "sub": _elemwise("Sub"),
    # NLP subset (round 4) — enough for a transformer encoder layer:
    "LayerNorm": _layer_norm,
    "layer_norm": _layer_norm,
    "LeakyReLU": _leaky_relu,
    "leaky_relu": _leaky_relu,
    "erf": _simple("Erf"),
    "Embedding": _embedding,
    "embedding": _embedding,
    "batch_dot": _batch_dot,
    "transpose": _transpose_exp,
    "Reshape": _reshape_exp,
    "reshape": _reshape_exp,
    "slice_axis": _slice_axis_exp,
    "expand_dims": _expand_dims_exp,
    "where": _where_exp,
    "broadcast_div": _elemwise("Div"),
    "div": _elemwise("Div"),
    "broadcast_sub": _elemwise("Sub"),
    "broadcast_power": _elemwise("Pow"),
    "broadcast_maximum": _elemwise("Max"),
    "broadcast_minimum": _elemwise("Min"),
    "maximum": _elemwise("Max"),
    "minimum": _elemwise("Min"),
}


def _scalar_op(onnx_op, reverse=False):
    """Scalar-arithmetic family (x op c, and c op x for the _r
    variants).  The constant is emitted in the TRACKED dtype of the
    tensor operand (export_model threads it via the private
    ``_onnx_in_dtype`` attr) so non-float32 graphs don't produce
    type-mismatched binary ops that strict runtimes reject.  Integer
    operands get a Cast-to-float32 mirroring the runtime's promotion
    (the scalar passes through float(), so e.g. int32/2 is TRUE
    division at runtime — an int ONNX Div would truncate)."""
    def conv(node, ins, out, attrs):
        dt = np.dtype(attrs.get("_onnx_in_dtype") or np.float32)
        val = float(attrs.get("scalar", 0.0))
        c = out + "__s"
        nodes = []
        data = ins[0]
        if np.issubdtype(dt, np.integer):
            # mirror the RUNTIME semantics: the scalar goes through
            # float(), so an integer tensor promotes to float32 (true
            # division included) — export a Cast, not an int constant
            # (ONNX integer Div truncates; the runtime's never does)
            data = out + "__f"
            nodes.append(_node("Cast", [ins[0]], [data], data,
                               {"to": P.FLOAT}))
            dt = np.dtype(np.float32)
        const = np.asarray(val, dtype=dt)
        operands = [c, data] if reverse else [data, c]
        nodes.append(_const(c, const))
        nodes.append(_node(onnx_op, operands, [out], out))
        return nodes
    return conv


_SCALAR_OPS = ("_mul_scalar", "_div_scalar", "_plus_scalar",
               "_minus_scalar", "_rminus_scalar", "_rdiv_scalar")

_TRANSLATIONS.update({
    "_mul_scalar": _scalar_op("Mul"),
    "_div_scalar": _scalar_op("Div"),
    "_plus_scalar": _scalar_op("Add"),
    "_minus_scalar": _scalar_op("Sub"),
    "_rminus_scalar": _scalar_op("Sub", reverse=True),
    "_rdiv_scalar": _scalar_op("Div", reverse=True),
})


_NP2ONNX = {"float32": P.FLOAT, "float64": P.DOUBLE, "int64": P.INT64,
            "int32": P.INT32, "int8": P.INT8, "uint8": P.UINT8,
            "float16": P.FLOAT16}


def export_model(sym, params, input_shapes, input_types=None,
                 onnx_file_path="model.onnx", verbose=False):
    """Reference ``mx.contrib.onnx.export_model``: Symbol + params →
    ONNX file.  ``input_shapes``/``input_types``: per data input
    (non-param vars, graph order; types default float32)."""
    params = dict({k.split(":", 1)[-1]: v for k, v in params.items()})
    # fix_gamma BatchNorms compute with gamma == 1 (runtime contract,
    # ops/nn_ops.py batch_norm); the exported initializer must match
    for node in sym._topo():
        if node.op in ("BatchNorm", "batch_norm") and \
                str(node.attrs.get("fix_gamma", True)).lower() in \
                ("true", "1"):
            if len(node.inputs) > 1:
                gname = node.inputs[1][0].name
                if gname in params:
                    params[gname] = params[gname] * 0 + 1  # ones_like
    # output shapes/dtypes for the declared ValueInfos
    try:
        shape_kwargs = {}
        di = 0
        for node in sym._topo():
            if node.is_var() and node.name not in params:
                shape_kwargs[node.name] = tuple(input_shapes[di])
                di += 1
        _, out_shapes, _ = sym.infer_shape(**shape_kwargs)
    except Exception:
        out_shapes = [() for _ in sym._heads]
    order = sym._topo()
    names = {}           # (id(node), oidx) -> onnx tensor name
    tdtypes = {}         # onnx tensor name -> np.dtype (best effort)
    nodes_out = []
    initializers = []
    graph_inputs = []
    data_idx = 0

    for node in order:
        if node.is_var():
            names[(id(node), 0)] = node.name
            if node.name in params:
                arr = np.asarray(params[node.name].asnumpy())
                tdtypes[node.name] = arr.dtype
                initializers.append(_tensor(node.name, arr))
            else:
                if data_idx >= len(input_shapes):
                    raise MXNetError(
                        f"no input shape provided for {node.name!r}")
                et = P.FLOAT
                tdtypes[node.name] = np.dtype(np.float32)
                if input_types is not None and data_idx < len(input_types):
                    dt = np.dtype(input_types[data_idx])
                    et = _NP2ONNX.get(dt.name, P.FLOAT)
                    tdtypes[node.name] = dt
                graph_inputs.append(
                    _value_info(node.name, input_shapes[data_idx], et))
                data_idx += 1
            continue
        trans = _TRANSLATIONS.get(node.op)
        if trans is None:
            raise MXNetError(
                f"ONNX export: op {node.op!r} has no translation "
                f"(supported: {sorted(_TRANSLATIONS)})")
        ins = [names[(id(s), oi)] for s, oi in node.inputs]
        out_name = node.name
        for i in range(node.num_outputs):
            names[(id(node), i)] = out_name if i == 0 else \
                f"{out_name}_out{i}"
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        # dtype flow for the translators that need it (_scalar_op): the
        # lookup result dtype follows the table, `where` follows its
        # branches (the condition is Cast to BOOL), everything else in
        # the subset follows its first dtype-known input
        if node.op in ("Embedding", "embedding", "where") and len(ins) > 1:
            out_dt = tdtypes.get(ins[1])
        else:
            out_dt = next((tdtypes[i] for i in ins if i in tdtypes), None)
        if out_dt is not None:
            attrs["_onnx_in_dtype"] = out_dt
            if node.op in _SCALAR_OPS and np.issubdtype(out_dt,
                                                        np.integer):
                # the runtime promotes int scalar-arithmetic to float32
                # (scalar passes through float()); the emitted Cast in
                # _scalar_op makes the exported output f32 too
                out_dt = np.dtype(np.float32)
            for i in range(node.num_outputs):
                tdtypes[names[(id(node), i)]] = out_dt
        nodes_out.extend(trans(node, ins, out_name, attrs))

    outputs = [_value_info(names[(id(n), oi)], shp or ())
               for (n, oi), shp in zip(sym._heads, out_shapes)]
    graph = b"".join(P.fbytes(1, nb) for nb in nodes_out)
    graph += P.fstr(2, "mxnet_tpu_exported")
    graph += b"".join(P.fbytes(5, t) for t in initializers)
    graph += b"".join(P.fbytes(11, vi) for vi in graph_inputs)
    graph += b"".join(P.fbytes(12, vo) for vo in outputs)

    opset = P.fint(2, _OPSET)  # default domain ""
    model = P.fint(1, _IR_VERSION)
    model += P.fstr(2, "mxnet_tpu")
    model += P.fstr(3, "0.1")
    model += P.fbytes(7, graph)
    model += P.fbytes(8, opset)

    with open(onnx_file_path, "wb") as f:
        f.write(model)
    if verbose:
        print(f"wrote {onnx_file_path}: {len(nodes_out)} nodes, "
              f"{len(initializers)} initializers")
    return onnx_file_path
