"""Model quantization driver: calibration + symbolic INT8 rewrite.

Reference: ``python/mxnet/contrib/quantization.py:?`` (``quantize_model``,
``quantize_net``) + ``src/operator/quantization/calibrate.cc:?``
(minmax/entropy calibration) — SURVEY §2.2 quantization row.

TPU-native: the rewrite is a pure-python pass over the native ``Symbol``
graph — Convolution/FullyConnected nodes become
``quantize_v2 → quantized_conv/fc → dequantize`` chains whose int8 matmuls
hit the MXU's int8×int8→int32 path.  Calibration runs the fp32 graph with
an executor monitor callback collecting per-layer output ranges (naive
min/max) or histograms (entropy/KL, the TensorRT-style optimal-threshold
search the reference implements in calibrate.cc).
"""
from __future__ import annotations

import re

import numpy as np

from ..base import MXNetError

_QUANTIZABLE = {"Convolution", "FullyConnected"}


# --- calibration -------------------------------------------------------------

def _collect_ranges(sym, arg_params, aux_params, calib_data, data_names,
                    num_examples, mode, ctx=None):
    """Run fp32 forward passes, recording per-layer output ranges.

    naive: running min/max.  entropy: 8001-bin histograms → KL-optimal
    thresholds (reference calibrate.cc).
    """
    from .. import context as _ctx_mod
    from .. import ndarray as nd

    stats = {}      # name -> [min, max]
    hists = {}      # name -> (hist, edges)
    # only quantizable nodes' first inputs are ever consumed as '_input0'
    # keys — skip everything else (weights repeat identically per batch)
    want_inputs = {f"{n.name}_input0" for n in sym._topo()
                   if n.op in _QUANTIZABLE}

    def cb(name, arr):
        # skip input records except quantizable nodes' first inputs
        # (match the generated suffix only — node names may contain
        # '_input' themselves)
        if re.search(r"_input\d+$", name) and name not in want_inputs:
            return
        a = arr.asnumpy()
        mn, mx = float(a.min()), float(a.max())
        if name in stats:
            stats[name][0] = min(stats[name][0], mn)
            stats[name][1] = max(stats[name][1], mx)
        else:
            stats[name] = [mn, mx]
        if mode == "entropy":
            amax = max(abs(mn), abs(mx), 1e-8)
            if name not in hists:
                hists[name] = np.histogram(a, bins=8001,
                                           range=(-amax, amax))
            else:
                h0, e0 = hists[name]
                if e0[-1] >= amax:
                    # existing edges cover the batch: accumulate in place
                    h2, _ = np.histogram(a, bins=8001,
                                         range=(e0[0], e0[-1]))
                    hists[name] = (h0 + h2, e0)
                else:
                    # widen: rebin the old histogram into the new edges
                    h, edges = np.histogram(a, bins=8001,
                                            range=(-amax, amax))
                    h2, _ = np.histogram((e0[:-1] + e0[1:]) / 2, bins=8001,
                                         range=(-amax, amax), weights=h0)
                    hists[name] = (h + h2, edges)

    seen = 0
    first = True
    exe = None
    for batch in calib_data:
        arrays = batch if isinstance(batch, (list, tuple)) else [batch]
        feed = dict(zip(data_names, arrays))
        if first:
            shapes = {k: v.shape for k, v in feed.items()}
            arg_shapes_full = dict(shapes)
            exe = sym.simple_bind(ctx or _ctx_mod.current_context(),
                                  grad_req="null", **arg_shapes_full)
            for k, v in arg_params.items():
                if k in exe.arg_dict:
                    exe.arg_dict[k]._data = v._data
            for k, v in (aux_params or {}).items():
                if k in exe.aux_dict:
                    exe.aux_dict[k]._data = v._data
            exe.set_monitor_callback(cb, monitor_all=True)
            first = False
        exe.forward(is_train=False, **feed)
        seen += arrays[0].shape[0]
        if num_examples is not None and seen >= num_examples:
            break
    if mode == "entropy":
        return {n: _optimal_threshold(*hists[n]) for n in hists}
    return {n: (mn, mx) for n, (mn, mx) in stats.items()}


def _smooth(p, eps=1e-4):
    is_zero = p == 0
    n_zero = is_zero.sum()
    n_nonzero = p.size - n_zero
    if n_nonzero == 0:
        return np.full_like(p, eps, dtype=np.float64)
    out = p.astype(np.float64)
    out[is_zero] = eps
    out[~is_zero] -= eps * n_zero / n_nonzero
    # redistribution may push tiny mass negative; keep strictly positive
    return np.maximum(out, eps * 0.1)


def _optimal_threshold(hist, edges, num_quantized_bins=255):
    """KL-divergence threshold search (reference calibrate.cc
    ``GetOptimalThreshold``): pick the symmetric clip range whose
    quantized distribution diverges least from the fp32 one."""
    hist = hist.astype(np.float64)
    num_bins = hist.size
    zero_bin = num_bins // 2
    best_kl, best_t = np.inf, float(edges[-1])
    # scan candidate thresholds from small to full range
    for i in range(num_quantized_bins // 2, num_bins // 2 + 1,
                   max((num_bins // 2) // 64, 1)):
        lo, hi = zero_bin - i, zero_bin + i + 1
        sliced = hist[lo:hi]
        # P: clipped distribution with outliers absorbed into edge bins
        p = sliced.copy()
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        if p.sum() == 0:
            continue
        # Q: built from the UNCLIPPED slice (TensorRT/calibrate.cc detail —
        # this is what penalizes thresholds that clip real mass: P's edge
        # spike has no counterpart in Q)
        factor = sliced.size / num_quantized_bins
        q = np.zeros_like(p, dtype=np.float64)
        for j in range(num_quantized_bins):
            s = int(np.floor(j * factor))
            e = int(np.ceil((j + 1) * factor))
            chunk = sliced[s:e]
            nz = (chunk != 0).sum()
            if nz:
                q[s:e] = np.where(chunk != 0, chunk.sum() / nz, 0)
        ps = _smooth(p / p.sum())
        qs = _smooth(q / max(q.sum(), 1e-12))
        kl = float(np.sum(ps * np.log(ps / qs)))
        if kl < best_kl:
            best_kl = kl
            best_t = float(edges[min(hi, edges.size - 1)])
    return (-best_t, best_t)


# --- graph rewrite -----------------------------------------------------------

def _int8_supported(node):
    """quantized_conv covers plain 2D convs only — grouped and 1D/3D
    convolutions stay fp32 (the reference excludes these per-backend via
    the same node-level check in its quantize pass)."""
    if node.op != "Convolution":
        return True
    if int(node.attrs.get("num_group", 1)) != 1:
        return False
    kernel = node.attrs.get("kernel")
    return kernel is None or len(tuple(kernel)) == 2


def _producer_range(node, calib_ranges):
    """Calibrated range of the tensor feeding ``node`` (the producing
    layer's recorded output range)."""
    if not node.inputs:
        return None
    src, oi = node.inputs[0]
    suffix = f"_output{oi}" if src.num_outputs > 1 else "_output"
    return calib_ranges.get(src.name + suffix)


def quantize_symbol(sym, excluded_sym_names=(), offline_params=(),
                    calib_ranges=None, quantized_dtype="int8",
                    param_shapes=None):
    """Rewrite a Symbol: quantizable nodes become int8 chains (reference
    ``QuantizeGraph`` pass, ``src/operator/quantization/
    quantize_graph_pass.cc:?``).  ``param_shapes`` are baked into the new
    graph's vars — a param that used to feed an FC/Conv (whose inference
    rule derived its shape) now feeds ``quantize_v2``, which can't."""
    import mxnet_tpu.symbol as S

    calib_ranges = calib_ranges or {}
    param_shapes = param_shapes or {}
    excluded = set(excluded_sym_names)
    cache = {}

    def convert(node, oidx):
        key = (id(node), oidx)
        if key in cache:
            return cache[key]
        if node.is_var():
            out = S.var(node.name,
                        shape=param_shapes.get(node.name),
                        attr=({"__is_aux__": True}
                              if node.attrs.get("__is_aux__") else None))
            cache[(id(node), 0)] = out
            return out
        ins = [convert(s, oi) for s, oi in node.inputs]
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        if node.op in _QUANTIZABLE and node.name not in excluded and \
                _int8_supported(node):
            data, weight = ins[0], ins[1]
            no_bias = str(attrs.get("no_bias", False)).lower() in \
                ("true", "1")
            bias = None if no_bias else ins[2]
            # calibrated range of THIS layer's input: prefer the directly
            # recorded input range (monitor_all), else the producer's
            # output range
            rng = calib_ranges.get(f"{node.name}_input0") \
                or _producer_range(node, calib_ranges)
            qkw = {}
            if rng is not None:
                qkw = {"min_calib_range": float(rng[0]),
                       "max_calib_range": float(rng[1])}
            # conv requires symmetric int8 data (zero-padding exactness)
            ddtype = "int8" if node.op == "Convolution" else quantized_dtype
            qd = S.quantize_v2(data, out_type=ddtype,
                               name=f"{node.name}_data_quantize", **qkw)
            qw = S.quantize_v2(weight, out_type="int8",
                               name=f"{node.name}_weight_quantize")
            # int8 compute without bias; fp32 bias added after dequantize
            # (exact — avoids requantizing bias into the accum scale)
            qargs = [qd[0], qw[0], qd[1], qd[2], qw[1], qw[2]]
            qop = (S.quantized_conv if node.op == "Convolution"
                   else S.quantized_fully_connected)
            q = qop(*qargs, name=f"quantized_{node.name}", no_bias=True,
                    **{k: v for k, v in attrs.items() if k != "no_bias"})
            out = S.dequantize(q[0], q[1], q[2],
                               name=f"{node.name}_dequantize")
            if bias is not None:
                if node.op == "Convolution":
                    b = S.reshape(bias, shape=(1, -1, 1, 1),
                                  name=f"{node.name}_bias_reshape")
                else:
                    b = bias
                out = S.broadcast_add(out, b, name=f"{node.name}_bias_add")
            cache[key] = out
            return out
        from ..symbol.symbol import _sym_op as _builder

        built = _builder(node.op)(*ins, name=node.name, **attrs)
        for i in range(node.num_outputs):
            cache[(id(node), i)] = built[i] if node.num_outputs > 1 \
                else built
        return cache[key]

    heads = [convert(n, oi) for n, oi in sym._heads]
    return S.Group(heads) if len(heads) > 1 else heads[0]


def quantize_params(qsym, arg_params):
    """Pass-through params for vars still present in the quantized graph
    (weights stay fp32 here; quantize_v2 nodes quantize at bind time —
    the reference's offline variant precomputes int8 copies instead)."""
    needed = set(qsym.list_arguments()) | set(qsym.list_auxiliary_states())
    return {k: v for k, v in arg_params.items() if k in needed}


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=None, calib_mode="entropy",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", **kwargs):
    """Reference ``mx.contrib.quantization.quantize_model``: returns
    (quantized symbol, params, aux params)."""
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise MXNetError(f"bad quantized_dtype {quantized_dtype!r}")
    if quantized_dtype == "auto":
        quantized_dtype = "int8"
    ranges = None
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError(
                f"calib_mode={calib_mode!r} requires calib_data")
        ranges = _collect_ranges(sym, arg_params, aux_params, calib_data,
                                 data_names, num_calib_examples,
                                 calib_mode, ctx=ctx)
    qsym = quantize_symbol(sym, excluded_sym_names or (),
                           calib_ranges=ranges,
                           quantized_dtype=quantized_dtype,
                           param_shapes={k: v.shape
                                         for k, v in arg_params.items()})
    qarg = quantize_params(qsym, arg_params)
    return qsym, qarg, dict(aux_params or {})


def quantize_net(network, quantized_dtype="int8", exclude_layers=None,
                 calib_data=None, data_shapes=None, calib_mode="naive",
                 num_calib_examples=None, ctx=None, **kwargs):
    """Reference ``quantize_net``: quantize a Gluon network in place.

    TPU-native redesign: instead of exporting to a symbol and re-importing
    (the reference flow), Dense/Conv2D layers are rewritten directly —
    their ``hybrid_forward`` becomes a quantize→int8-op→dequantize chain
    with input ranges calibrated by forward pre-hooks.  The rewritten net
    hybridizes into a single XLA program with int8 MXU matmuls.  Returns
    the network."""
    import types

    from ..gluon import nn

    if calib_data is None:
        raise MXNetError("quantize_net requires calib_data")
    # hybridized blocks replay cached graphs — pre-hooks would never fire
    # (or see tracers); run calibration imperatively, restore after rewrite
    was_hybrid = []

    def _dehybridize(b):
        if getattr(b, "_active", False):
            was_hybrid.append((b, dict(getattr(b, "_flags", {}))))
            b._active = False
        if hasattr(b, "_clear_cached_op"):
            b._cached_op = None

    network.apply(_dehybridize)
    excluded = set(exclude_layers or ())
    targets = []

    def visit(block):
        for child in block._children.values():
            if isinstance(child, (nn.Dense, nn.Conv2D)) and \
                    not getattr(child, "_transposed", False) and \
                    child.name not in excluded:
                targets.append(child)
            visit(child)

    visit(network)
    # 1) calibrate input ranges with pre-hooks
    ranges = {}
    handles = []

    def mk_hook(layer):
        def hook(blk, inputs):
            a = inputs[0].asnumpy()
            mn, mx = float(a.min()), float(a.max())
            if id(layer) in ranges:
                r = ranges[id(layer)]
                ranges[id(layer)] = (min(r[0], mn), max(r[1], mx))
            else:
                ranges[id(layer)] = (mn, mx)
        return hook

    for t in targets:
        handles.append(t.register_forward_pre_hook(mk_hook(t)))
    seen = 0
    for batch in calib_data:
        arrays = batch if isinstance(batch, (list, tuple)) else [batch]
        network(*arrays)
        seen += arrays[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    for h in handles:
        h.detach()

    # 2) rewrite layer forwards
    def dense_forward(rng, units, flatten):
        def hybrid_forward(self, F, x, weight, bias=None):
            qd = F.quantize_v2(x, out_type=quantized_dtype,
                               min_calib_range=rng[0],
                               max_calib_range=rng[1])
            qw = F.quantize_v2(weight, out_type="int8")
            q = F.quantized_fully_connected(
                qd[0], qw[0], qd[1], qd[2], qw[1], qw[2], no_bias=True,
                num_hidden=units, flatten=flatten)
            out = F.dequantize(q[0], q[1], q[2])
            if bias is not None:
                out = F.broadcast_add(out, bias)
            if self.act is not None:
                out = self.act(out)
            return out
        return hybrid_forward

    def conv_forward(rng, layer):
        def hybrid_forward(self, F, x, weight, bias=None):
            # conv requires symmetric int8 data (zero-padding exactness)
            qd = F.quantize_v2(x, out_type="int8",
                               min_calib_range=rng[0],
                               max_calib_range=rng[1])
            qw = F.quantize_v2(weight, out_type="int8")
            q = F.quantized_conv(
                qd[0], qw[0], qd[1], qd[2], qw[1], qw[2], no_bias=True,
                kernel=layer._kernel, stride=layer._strides,
                pad=layer._padding, dilate=layer._dilation,
                num_filter=layer._channels)
            out = F.dequantize(q[0], q[1], q[2])
            if bias is not None:
                out = F.broadcast_add(
                    out, F.reshape(bias, shape=(1, -1, 1, 1)))
            if self.act is not None:
                out = self.act(out)
            return out
        return hybrid_forward

    for t in targets:
        rng = ranges.get(id(t))
        if rng is None:
            continue  # layer never ran during calibration
        if isinstance(t, nn.Dense):
            fwd = dense_forward(rng, t._units, t._flatten)
        else:
            if getattr(t, "_groups", 1) != 1:
                continue  # grouped conv keeps fp32 (rare; exactness first)
            fwd = conv_forward(rng, t)
        t.hybrid_forward = types.MethodType(fwd, t)
        t._clear_cached_op()
    # restore hybridization: fresh traces now capture the int8 graph
    for b, flags in was_hybrid:
        b._active = True
        b._flags = flags
        b._cached_op = None
    return network
