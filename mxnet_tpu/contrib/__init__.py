"""Top-level ``mx.contrib`` namespace.

Reference: ``python/mxnet/contrib/__init__.py:?`` — amp, quantization,
onnx, ndarray/symbol contrib re-exports (SURVEY §2.4).
"""
from .. import amp  # noqa: F401
from . import quantization  # noqa: F401
from ..ndarray import contrib as ndarray  # noqa: F401
from ..symbol import contrib as symbol  # noqa: F401
