"""NDArray: the imperative tensor.

Reference: ``include/mxnet/ndarray.h:?`` + ``src/ndarray/ndarray.cc:?`` — a
chunk (storage handle) + shape/dtype/context/storage-type + a dependency-
engine variable; every op on it is pushed async to the engine, and python
blocks only at ``WaitToRead``/``asnumpy``.

TPU-native redesign: an NDArray is a mutable *handle* to an immutable
``jax.Array``.  Mutation (``x[:] = ...``, ``x += y``, optimizer updates)
rebinds the handle to a new functional value — the version-bump analog of the
reference engine's write-var sequencing.  Asynchrony comes from jax's own
async dispatch (device work is enqueued, python continues;
``wait_to_read`` == ``block_until_ready``), so the reference's threaded
engine (``src/engine/threaded_engine_perdevice.cc:?``) has no separate
replica here — XLA + the jax runtime play that role, as cuDNN/cuBLAS played
the kernel role for the reference.

Autograd wiring (``_node``/``_oidx``/``_req_grad``/``_grad``) is documented
in mxnet_tpu/autograd.py.
"""
from __future__ import annotations

from builtins import slice as builtins_slice

import numpy as np

from ..base import MXNetError, resolve_dtype
from ..context import Context, current_context
from .. import engine as _engine
from .. import telemetry
from ..telemetry import memwatch as _mw
from .. import sanitizer as _san

# hot-path refs bound on first arithmetic dispatch: the operator dunders
# run once per imperative op, and a per-call ``import jax.numpy`` /
# relative import costs ~1 us each — real money at bulked dispatch rates
_jnp = None
_apply_op = None
_sparse_mod = None
_sparse_base = ()  # isinstance-safe placeholder until _bind_arith runs


def _bind_arith():
    global _jnp, _apply_op, _sparse_mod, _sparse_base
    import jax.numpy as jnp

    from ..ops.registry import apply_op
    from . import sparse

    _apply_op = apply_op
    _sparse_mod = sparse
    _sparse_base = sparse.BaseSparseNDArray
    _jnp = jnp
    return jnp

#: placeholder class for buffers pending in a deferred engine segment
#: (bound once: the _data fast path is a single class-identity test)
_Pending = _engine._PendingArray


def _ctx_from_raw(raw) -> Context:
    try:
        dev = raw.device  # jax.Array
    except Exception:
        return current_context()
    if dev is None or not hasattr(dev, "platform"):
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


def _is_tracer(x):
    import jax.core

    return isinstance(x, jax.core.Tracer)


def creation_place(raw, ctx=None):
    """Placement for newly-created arrays.

    Under an active device mesh (mxnet_tpu/parallel) the mesh IS the
    context: creations land replicated over it so eager math against
    mesh-placed parameters stays consistent — the TPU analog of the
    reference's default-ctx placement.  Otherwise place on ``ctx`` when
    given.  Tracers (inside a CachedOp jit) pass through untouched."""
    import jax

    if _is_tracer(raw):
        return raw
    from .. import parallel

    mesh = parallel.current_mesh()
    if mesh is not None:
        return jax.device_put(raw, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()))
    if ctx is not None:
        return jax.device_put(raw, ctx.device)
    return raw


def _to_raw(value, dtype=None, ctx=None):
    """Coerce python/numpy input to a jax.Array (on ctx if given).

    Placement rule: host payloads and explicit-ctx requests go through
    ``creation_place`` (mesh-aware); device arrays with no ctx — op
    outputs — keep their propagated sharding untouched."""
    import jax
    import jax.numpy as jnp

    if isinstance(value, NDArray):
        raw = value._data
        if _san._enabled:
            _san.check(raw, "wrap")
        if dtype is not None and np.dtype(dtype) != raw.dtype:
            raw = raw.astype(dtype)
        if ctx is not None:
            raw = creation_place(raw, ctx)
        return raw
    is_device = isinstance(value, jax.Array)
    if dtype is None and isinstance(value, (list, tuple, float, int)):
        # MXNet semantics: python payloads always become float32
        dtype = np.float32
    raw = jnp.asarray(value, dtype=dtype)
    if not is_device or ctx is not None:
        raw = creation_place(raw, ctx)
    return raw


class NDArray:
    """A tensor handle with MXNet NDArray semantics over ``jax.Array``."""

    __slots__ = ("_raw", "_node", "_oidx", "_req_grad", "_grad", "_grad_req",
                 "__weakref__")

    # make numpy defer to us: NDArray.__radd__ etc. win over np.ndarray ops
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None, dtype=None):
        self._raw = _to_raw(data, dtype=dtype, ctx=ctx)
        if _mw._enabled:
            _mw.track(self._raw)
        self._node = None
        self._oidx = 0
        self._req_grad = False
        self._grad = None
        self._grad_req = "null"

    # -- the raw handle ------------------------------------------------------
    # ``_data`` is the pending-handle state of the deferred engine: while
    # this array's producing op sits in a pending bulk segment, ``_raw``
    # holds a placeholder and ANY ``_data`` read — every host sync and
    # every dispatch path in the tree goes through one — materializes by
    # flushing the segment.  The non-pending cost is one class-identity
    # test.  See mxnet_tpu/engine.py and docs/engine.md.

    @property
    def _data(self):
        raw = self._raw
        if raw.__class__ is _Pending:
            raw = _engine._materialize(raw)
            self._raw = raw
            if _mw._enabled:
                _mw.track(raw)
        return raw

    @_data.setter
    def _data(self, value):
        self._raw = value
        if _mw._enabled:
            _mw.track(value)

    # -- basic properties ----------------------------------------------------
    @property
    def shape(self):
        return tuple(self._raw.shape)

    @property
    def dtype(self):
        return np.dtype(self._raw.dtype)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._raw.ndim

    @property
    def context(self) -> Context:
        return _ctx_from_raw(self._raw)

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return self.transpose()

    # -- host sync -----------------------------------------------------------
    def asnumpy(self) -> np.ndarray:
        """Blocking device→host copy (reference: ``WaitToRead`` + copy,
        src/ndarray/ndarray.cc:?)."""
        if _san._enabled:
            _san.check(self._data, "asnumpy")
        telemetry.count("host_sync")
        try:
            return np.asarray(self._data)
        except Exception as exc:
            if _mw._enabled:
                _mw.annotate_oom(exc, context="asnumpy")
            raise

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        """Block until the value is computed (engine ``WaitForVar`` analog)."""
        if _san._enabled:
            _san.check(self._data, "wait_to_read")
        telemetry.count("host_sync")
        try:
            self._data.block_until_ready()
        except AttributeError:
            pass
        except Exception as exc:
            if _mw._enabled:
                _mw.annotate_oom(exc, context="wait_to_read")
            raise
        return self

    wait_to_write = wait_to_read

    # -- conversion / movement ----------------------------------------------
    def astype(self, dtype, copy=True):
        dt = resolve_dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        from ..ops.registry import apply_op

        return apply_op(lambda a: a.astype(dt), self, name="cast")

    def copy(self):
        from ..ops.registry import apply_op

        return apply_op(lambda a: a + 0 if a.dtype != np.bool_ else a.copy(),
                        self, name="copy")

    def copyto(self, other):
        """Copy into another NDArray (shape must match) or to a Context."""
        if isinstance(other, Context):
            return self.as_in_context(other)
        if not isinstance(other, NDArray):
            raise MXNetError("copyto target must be NDArray or Context")
        if other.shape != self.shape:
            raise MXNetError(
                f"copyto shape mismatch {self.shape} vs {other.shape}")
        if _san._enabled:
            _san.check(self._data, "copyto")
        import jax

        other._data = jax.device_put(
            self._data.astype(other.dtype), other.context.device)
        return other

    def as_in_context(self, ctx: Context):
        import jax

        if ctx == self.context:
            return self
        out = NDArray.__new__(NDArray)
        out._data = jax.device_put(self._data, ctx.device)
        out._node, out._oidx = self._node, self._oidx
        out._req_grad, out._grad, out._grad_req = False, None, "null"
        return out

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def asnative(self):
        """The raw jax.Array (TPU-native escape hatch; analog of DLPack
        interop, reference src/ndarray/ndarray.cc:? ``ToDLPack``)."""
        if _san._enabled:
            _san.check(self._data, "asnative")
        return self._data

    @property
    def _donated(self):
        """Donation-poison flag (``MXNET_SANITIZE_DONATION=1``): the site
        string of the jitted call this array's buffer was donated to, or
        None while the buffer is live.  Set by the donating dispatch
        paths (trainer/step_fusion/optimizer), cleared when the holder
        is rebound to a fresh result buffer."""
        return _san.site_of(self._data)

    # -- autograd ------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer; detaches from any recorded graph
        (reference: python/mxnet/ndarray/ndarray.py:? ``attach_grad``)."""
        import jax.numpy as jnp

        self._node = None
        self._oidx = 0
        self._grad_req = grad_req
        self._req_grad = grad_req != "null"
        if self._req_grad:
            g = NDArray.__new__(NDArray)
            g._data = jnp.zeros(self.shape, self.dtype)
            g._node, g._oidx = None, 0
            g._req_grad, g._grad, g._grad_req = False, None, "null"
            self._grad = g
        else:
            self._grad = None

    @property
    def grad(self):
        return self._grad

    def zero_grad(self):
        if self._grad is not None:
            import jax.numpy as jnp

            self._grad._data = jnp.zeros(self.shape, self.dtype)

    def detach(self):
        out = NDArray.__new__(NDArray)
        out._raw = self._raw  # placeholder moves without materializing
        out._node, out._oidx = None, 0
        out._req_grad, out._grad, out._grad_req = False, None, "null"
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad], retain_graph=retain_graph,
                          train_mode=train_mode)

    def _alias(self):
        """Snapshot handle used to break self-reference when an in-place op
        is recorded (the reference versions engine vars instead)."""
        out = NDArray.__new__(NDArray)
        out._raw = self._raw  # placeholder moves without materializing
        out._node, out._oidx = self._node, self._oidx
        out._req_grad, out._grad, out._grad_req = (
            self._req_grad, self._grad, self._grad_req)
        return out

    # -- arithmetic ----------------------------------------------------------
    def _binary(self, other, jf, name, reflected=False):
        apply_op = _apply_op
        if apply_op is None:
            _bind_arith()
            apply_op = _apply_op
        if isinstance(other, NDArray):
            if reflected:
                return apply_op(lambda a, b: jf(b, a), self, other, name=name)
            return apply_op(lambda a, b: jf(a, b), self, other, name=name)
        if isinstance(other, _sparse_base):
            _sp = _sparse_mod
            canon = {"add": "add", "sub": "subtract", "mul": "multiply",
                     "div": "divide"}.get(name, name)
            if reflected:
                return _sp.dispatch_binary(canon, jf, other, self)
            return _sp.dispatch_binary(canon, jf, self, other)
        c = other
        if type(c) is int and name not in ("pow", "rpow") and \
                np.dtype(self._raw.dtype).kind == "f":
            # a python int baked into the deferred closure is keyed by
            # VALUE — one compiled segment per distinct constant (the
            # ``x / batch_size`` retrace trap); as a float the engine
            # lifts it to a runtime scalar and every value replays one
            # segment.  Exact for float arrays (same weak promotion);
            # pow is excluded: integer exponents lower to repeated
            # multiplication, float ones to exp/log whose negative-base
            # results differ.
            c = float(c)

        if reflected:
            return apply_op(lambda a: jf(c, a), self, name=name)
        return apply_op(lambda a: jf(a, c), self, name=name)

    def _inplace(self, other, jf, name):
        out = self._alias()._binary(other, jf, name)
        self._raw, self._node, self._oidx = out._raw, out._node, out._oidx
        return self

    def __add__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.add, "add")

    __radd__ = __add__

    def __sub__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.subtract, "sub")

    def __rsub__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.subtract, "rsub", reflected=True)

    def __mul__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.multiply, "mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.divide, "div")

    def __rtruediv__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.divide, "rdiv", reflected=True)

    def __floordiv__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.floor_divide, "floordiv")

    def __mod__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.mod, "mod")

    def __rmod__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.mod, "rmod", reflected=True)

    def __pow__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.power, "pow")

    def __rpow__(self, o):
        jnp = _jnp or _bind_arith()

        return self._binary(o, jnp.power, "rpow", reflected=True)

    def __matmul__(self, o):
        from . import dot

        return dot(self, o)

    def __neg__(self):
        apply_op = _apply_op or (_bind_arith() and _apply_op)
        return apply_op(lambda a: -a, self, name="neg")

    def __abs__(self):
        from ..ops.registry import apply_op
        jnp = _jnp or _bind_arith()

        return apply_op(jnp.abs, self, name="abs")

    def __iadd__(self, o):
        jnp = _jnp or _bind_arith()

        return self._inplace(o, jnp.add, "iadd")

    def __isub__(self, o):
        jnp = _jnp or _bind_arith()

        return self._inplace(o, jnp.subtract, "isub")

    def __imul__(self, o):
        jnp = _jnp or _bind_arith()

        return self._inplace(o, jnp.multiply, "imul")

    def __itruediv__(self, o):
        jnp = _jnp or _bind_arith()

        return self._inplace(o, jnp.divide, "idiv")

    # -- comparisons (elementwise 0/1 arrays in the operand dtype, matching
    #    the reference's comparison ops) --------------------------------------
    def _cmp(self, o, jf, name):
        jnp = _jnp or _bind_arith()

        dt = self.dtype if self.dtype != np.bool_ else np.float32
        return self._binary(o, lambda a, b: jf(a, b).astype(dt), name)

    def __eq__(self, o):
        jnp = _jnp or _bind_arith()

        return self._cmp(o, jnp.equal, "eq")

    def __ne__(self, o):
        jnp = _jnp or _bind_arith()

        return self._cmp(o, jnp.not_equal, "ne")

    def __gt__(self, o):
        jnp = _jnp or _bind_arith()

        return self._cmp(o, jnp.greater, "gt")

    def __ge__(self, o):
        jnp = _jnp or _bind_arith()

        return self._cmp(o, jnp.greater_equal, "ge")

    def __lt__(self, o):
        jnp = _jnp or _bind_arith()

        return self._cmp(o, jnp.less, "lt")

    def __le__(self, o):
        jnp = _jnp or _bind_arith()

        return self._cmp(o, jnp.less_equal, "le")

    __hash__ = object.__hash__  # identity hash despite elementwise __eq__

    # -- indexing ------------------------------------------------------------
    @staticmethod
    def _raw_key(key):
        """Unwrap NDArray keys to raw arrays; float index arrays (the
        reference's argmax/argsort/topk return float32 indices by design)
        are cast to int so reference-style ``x[x.argmax()]`` works."""
        def one(k):
            if isinstance(k, NDArray):
                r = k._data
            elif isinstance(k, np.ndarray):
                r = k
            else:
                return k
            if np.issubdtype(np.dtype(r.dtype), np.floating) or \
                    np.dtype(r.dtype).name == "bfloat16":
                r = r.astype(np.int32)
            return r

        if isinstance(key, tuple):
            return tuple(one(k) for k in key)
        return one(key)

    @staticmethod
    def _is_full_key(key):
        return key is None or key is Ellipsis or (
            isinstance(key, builtins_slice) and key.start is None
            and key.stop is None and key.step is None)

    def __getitem__(self, key):
        from ..ops.registry import apply_op

        if self._raw.__class__ is _Pending:
            # indexing a pending array is a sync point of the deferred
            # engine (the flush contract, docs/engine.md); the getitem
            # itself may then start a fresh segment
            _engine.flush("host_sync")
        rkey = NDArray._raw_key(key)
        return apply_op(lambda a: a[rkey], self, name="getitem")

    def __setitem__(self, key, value):
        """Functional in-place write (reference mutates the chunk under an
        engine write-var; we rebind the handle).  Tape semantics: the write
        is recorded as an op, so gradients flow into the assigned value and
        stop flowing into the overwritten region."""
        from ..ops.registry import apply_op
        import jax.numpy as jnp

        if NDArray._is_full_key(key):
            # x[:] = v → full overwrite: the result depends only on v
            shape, dt = self.shape, self.dtype
            if isinstance(value, NDArray):
                out = apply_op(
                    lambda v: jnp.broadcast_to(v.astype(dt), shape),
                    value, name="setitem_full")
            else:
                out = NDArray(jnp.full(shape, value, dt))
            self._raw, self._node, self._oidx = (
                out._raw, out._node, out._oidx)
            return
        rkey = NDArray._raw_key(key)
        if isinstance(value, NDArray):
            out = apply_op(
                lambda a, v: a.at[rkey].set(v.astype(a.dtype)),
                self._alias(), value, name="setitem")
        else:
            out = apply_op(
                lambda a: a.at[rkey].set(jnp.asarray(value).astype(a.dtype)),
                self._alias(), name="setitem")
        self._raw, self._node, self._oidx = out._raw, out._node, out._oidx

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asnumpy())
        raise MXNetError(
            "The truth value of an NDArray with multiple elements is "
            "ambiguous.")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self):
        try:
            payload = str(self.asnumpy())
        except Exception as e:  # pragma: no cover
            payload = f"<unevaluated: {e}>"
        return (f"\n{payload}\n<NDArray {'x'.join(map(str, self.shape))} "
                f"@{self.context}>")

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # -- method forms of common ops (delegate to the nd namespace) -----------
    def _nd(self):
        from .. import ndarray as nd

        return nd

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self._nd().reshape(self, shape=shape)

    def reshape_like(self, other):
        return self._nd().reshape_like(self, other)

    def transpose(self, axes=None):
        return self._nd().transpose(self, axes=axes)

    def swapaxes(self, dim1, dim2):
        return self._nd().swapaxes(self, dim1, dim2)

    def flatten(self):
        return self._nd().flatten(self)

    def expand_dims(self, axis):
        return self._nd().expand_dims(self, axis=axis)

    def squeeze(self, axis=None):
        return self._nd().squeeze(self, axis=axis)

    def broadcast_to(self, shape):
        return self._nd().broadcast_to(self, shape=shape)

    def broadcast_like(self, other):
        return self._nd().broadcast_like(self, other)

    def tile(self, reps):
        return self._nd().tile(self, reps=reps)

    def repeat(self, repeats, axis=None):
        return self._nd().repeat(self, repeats=repeats, axis=axis)

    def flip(self, axis):
        return self._nd().flip(self, axis=axis)

    def sum(self, axis=None, keepdims=False):
        return self._nd().sum(self, axis=axis, keepdims=keepdims)

    def nansum(self, axis=None, keepdims=False):
        return self._nd().nansum(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._nd().mean(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._nd().prod(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return self._nd().max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return self._nd().min(self, axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._nd().norm(self, ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._nd().argmax(self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._nd().argmin(self, axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        return self._nd().argsort(self, axis=axis, is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return self._nd().sort(self, axis=axis, is_ascend=is_ascend)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return self._nd().topk(self, axis=axis, k=k, ret_typ=ret_typ,
                               is_ascend=is_ascend)

    def clip(self, a_min=None, a_max=None):
        return self._nd().clip(self, a_min=a_min, a_max=a_max)

    def abs(self):
        return self.__abs__()

    def sign(self):
        return self._nd().sign(self)

    def exp(self):
        return self._nd().exp(self)

    def log(self):
        return self._nd().log(self)

    def sqrt(self):
        return self._nd().sqrt(self)

    def square(self):
        return self._nd().square(self)

    def sigmoid(self):
        return self._nd().sigmoid(self)

    def tanh(self):
        return self._nd().tanh(self)

    def relu(self):
        return self._nd().relu(self)

    def softmax(self, axis=-1):
        return self._nd().softmax(self, axis=axis)

    def log_softmax(self, axis=-1):
        return self._nd().log_softmax(self, axis=axis)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return self._nd().dot(self, other, transpose_a=transpose_a,
                              transpose_b=transpose_b)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return self._nd().one_hot(self, depth=depth, on_value=on_value,
                                  off_value=off_value)

    def take(self, indices, axis=0, mode="clip"):
        return self._nd().take(self, indices, axis=axis, mode=mode)

    def slice_axis(self, axis, begin, end):
        return self._nd().slice_axis(self, axis=axis, begin=begin, end=end)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return self._nd().split(self, num_outputs=num_outputs, axis=axis,
                                squeeze_axis=squeeze_axis)

    def zeros_like(self):
        return self._nd().zeros_like(self)

    def ones_like(self):
        return self._nd().ones_like(self)

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse

        return sparse.cast_storage(self, stype)
