"""The ``mx.nd`` namespace: NDArray + the generated-op surface.

Reference: ``python/mxnet/ndarray/__init__.py:?`` — op wrappers are
*generated at import time* from the C++ registry (``ndarray/register.py:?``).
Here the ops are python functions registered in mxnet_tpu.ops; this module
re-exports them plus the creation functions, so ``mx.nd.<op>`` resolves the
same names as the reference.
"""
from __future__ import annotations

import numpy as _np

from ..base import resolve_dtype as _resolve_dtype
from ..context import current_context
from .ndarray import NDArray

# op namespaces (import order matters only for readability)
from ..ops.elemwise import *  # noqa: F401,F403
from ..ops.tensor import *  # noqa: F401,F403
from ..ops.nn_ops import *  # noqa: F401,F403
from ..ops.rnn_ops import *  # noqa: F401,F403
from ..ops.attention import *  # noqa: F401,F403
from ..ops.output_ops import *  # noqa: F401,F403
from ..ops.contrib import *  # noqa: F401,F403  (legacy top-level names)
from ..ops.quantization import *  # noqa: F401,F403
from ..operator import custom as Custom  # noqa: F401  (mx.nd.Custom)
from . import contrib  # noqa: F401  (mx.nd.contrib namespace)
from ..ops import registry as _registry

# random sampling lives in mx.nd.random too (reference parity)
from .. import random as random  # noqa: F401
from ..random import uniform as random_uniform  # noqa: F401
from ..random import normal as random_normal  # noqa: F401
from ..random import shuffle, multinomial, sample_multinomial  # noqa: F401


# --- creation (reference src/operator/tensor/init_op.cc:?) ------------------

def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (reference ``mx.nd.array``)."""
    if isinstance(source_array, NDArray):
        out = source_array.astype(dtype) if dtype else source_array.copy()
        return out.as_in_context(ctx) if ctx else out
    return NDArray(source_array, ctx=ctx or current_context(),
                   dtype=_resolve_dtype(dtype))


def zeros(shape, ctx=None, dtype=None, **kwargs):
    import jax.numpy as jnp

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.zeros(shape, _resolve_dtype(dtype) or _np.float32),
                   ctx=ctx or current_context())


def ones(shape, ctx=None, dtype=None, **kwargs):
    import jax.numpy as jnp

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.ones(shape, _resolve_dtype(dtype) or _np.float32),
                   ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype=None, **kwargs):
    import jax.numpy as jnp

    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.full(shape, val, _resolve_dtype(dtype) or _np.float32),
                   ctx=ctx or current_context())


def empty(shape, ctx=None, dtype=None):
    # no uninitialised memory on an immutable-array runtime; zeros is the
    # semantically safe stand-in
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None,
           **kwargs):
    """Reference ``arange``: evenly spaced values in ``[start, stop)``,
    each repeated ``repeat`` times."""
    import jax.numpy as jnp

    r = jnp.arange(start, stop, step, _resolve_dtype(dtype) or _np.float32)
    if repeat > 1:
        r = jnp.repeat(r, repeat)
    return NDArray(r, ctx=ctx or current_context())


# creation op with no tensor inputs: registered so the executor can
# evaluate the zero-input graph node mx.sym.arange builds (symbol.py
# defines the builder explicitly so positional start/stop work)
_registry.defop("arange")(arange)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    import jax.numpy as jnp

    return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint,
                                dtype=_resolve_dtype(dtype) or _np.float32),
                   ctx=ctx or current_context())


def eye(N, M=0, k=0, ctx=None, dtype=None, **kwargs):
    import jax.numpy as jnp

    return NDArray(jnp.eye(N, M or None, k,
                           _resolve_dtype(dtype) or _np.float32),
                   ctx=ctx or current_context())


def full_like(data, fill_value, **kwargs):
    import jax.numpy as jnp

    return _registry.apply_op(lambda a: jnp.full_like(a, fill_value), data,
                              name="full_like")


def stop_gradient(data, **kwargs):
    """Reference ``stop_gradient``/``BlockGrad``."""
    return data.detach()


BlockGrad = stop_gradient


def to_dlpack_for_read(data):
    """Zero-copy DLPack export (reference ``mx.nd.to_dlpack_for_read``,
    src/ndarray/ndarray.cc:? interop via 3rdparty/dlpack, SURVEY §2.7).

    Returns the underlying buffer as a DLPack-protocol object (implements
    ``__dlpack__``/``__dlpack_device__``) — the modern exchange form every
    consumer (torch/np/jax ``from_dlpack``) accepts; legacy capsule-only
    consumers can call ``.__dlpack__()`` on it."""
    return data._data


def to_dlpack_for_write(data):
    """The reference's write-through DLPack export has no sound analog:
    jax buffers are immutable, so consumer writes could never become
    visible in the NDArray.  Raise rather than silently lose writes."""
    from ..base import MXNetError

    raise MXNetError(
        "to_dlpack_for_write is unsupported: jax/XLA buffers are "
        "immutable. Export with to_dlpack_for_read and copy, or write "
        "into a new array and assign it back")


def from_dlpack(capsule):
    """Import a DLPack capsule (or any __dlpack__ object) as NDArray."""
    import jax.numpy as jnp

    return NDArray(jnp.from_dlpack(capsule))


def waitall():
    """Block until all enqueued device work completes (reference
    ``mx.nd.waitall`` → ``Engine::WaitForAll``)."""
    import jax

    try:
        jax.block_until_ready(jax.numpy.zeros(()))
        jax.effects_barrier()
    except Exception:
        pass


def load(fname):
    """Load NDArrays (dict or list) from an MXNet-format ``.params`` file
    (reference ``mx.nd.load`` → ``NDArray::Load``, src/ndarray/ndarray.cc:?;
    binary layout in mxnet_tpu/serialization.py — files interchange with
    the reference)."""
    from .. import serialization

    return serialization.load_ndarrays(fname)


def save(fname, data):
    """Save a list or dict of NDArrays in the MXNet binary container
    (reference ``mx.nd.save``)."""
    from .. import serialization

    serialization.save_ndarrays(fname, data)


def concat_dim0(arrays):
    return concat(*arrays, dim=0)  # noqa: F405  (from ops.tensor)


# sparse lives in its own module (BCOO-backed); imported lazily to keep the
# base import light
from . import sparse  # noqa: E402,F401
from .sparse import cast_storage  # noqa: E402,F401  (mx.nd.cast_storage)
