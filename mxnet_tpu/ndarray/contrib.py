"""``mx.nd.contrib`` namespace.

Reference: ``python/mxnet/ndarray/contrib.py:?`` — generated wrappers for
``_contrib_*`` registered ops plus hand-written helpers (foreach,
while_loop, cond live here too).  Ops are defined in
``mxnet_tpu/ops/contrib.py``; this module re-exports them under the names
reference scripts use (``mx.nd.contrib.box_nms`` etc.).
"""
from __future__ import annotations

from ..ops.contrib import *  # noqa: F401,F403
from ..ops.contrib import __all__ as _contrib_all
from ..ops.tensor import boolean_mask  # noqa: F401
from ..ops.attention import (  # noqa: F401
    div_sqrt_dim, interleaved_matmul_selfatt_qk,
    interleaved_matmul_selfatt_valatt)
from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401

__all__ = list(_contrib_all) + [
    "boolean_mask", "div_sqrt_dim", "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt", "foreach", "while_loop", "cond"]
